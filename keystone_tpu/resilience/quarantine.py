"""Corrupt-record quarantine with a bad-fraction budget.

The reference framework inherited "skip the bad record, keep the job
alive" from Spark task semantics; the TPU port's ingest (tar decode
pool, streaming prefetcher) previously either dropped undecodable
records *silently* or died on the first one. A :class:`Quarantine`
makes the middle path explicit:

* a bad record is **skipped but accounted**: its source identity and
  reason land in the in-memory manifest (and, when ``manifest_path`` is
  set, an append-only JSONL file), the ``resilience.quarantine`` counter
  and the active :class:`~keystone_tpu.observability.PipelineTrace`
  record it;
* the fit **fails loudly** once bad records exceed the
  ``max_bad_fraction`` budget — graceful degradation, never silent data
  loss. The error names the last quarantined source.

Records are keyed by source identity (``archive.tar::member.jpg``), so
a resumed/replayed pass re-encountering the same bad record counts it
once — the property checkpoint/resume relies on.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..utils.guarded import TracedLock, guarded_by
from .events import record_event


class CorruptRecordError(Exception):
    """A record that can never be read correctly (truncated image,
    garbage bytes) — the NON-retryable counterpart of
    :class:`~keystone_tpu.resilience.retry.TransientError`: retrying a
    corrupt record wastes attempts, quarantining it is the answer."""


class QuarantineBudgetExceededError(RuntimeError):
    """Raised when quarantined records exceed ``max_bad_fraction``."""


@guarded_by("_lock", "records", "bad_count", "ok_count", "_keys")
class Quarantine:
    """Skip-but-account sink for corrupt records; see module docstring.

    ``max_bad_fraction`` is the budget: the quarantine raises once
    ``bad > max_bad_fraction * max(records_seen, min_records)``. The
    ``min_records`` floor keeps a bad record early in the stream (1 bad
    of 2 seen = 50%) from killing a run whose true bad fraction is tiny;
    it also makes the budget check safe during a checkpoint-resume
    replay, where bad counts are restored before good records recount.

    Thread model: decode-pool workers quarantine records concurrently
    while the consumer thread snapshots ``state()`` for a checkpoint —
    counts, keys, the manifest tail, AND the JSONL manifest append all
    happen under the one lock, so a snapshot can never see (or the
    file never hold) a half-applied record. (The JSONL append used to
    run outside the lock: two workers could interleave, and a
    checkpointed ``state()`` could count a record whose manifest line
    was not yet durable — found by the guarded-by pass, PR 7.)
    """

    #: raw manifest entries retained in memory (counts stay exact)
    MANIFEST_TAIL = 1000

    def __init__(self, max_bad_fraction: float = 0.01,
                 min_records: int = 100,
                 manifest_path: Optional[str] = None,
                 label: str = "ingest"):
        if not 0.0 <= max_bad_fraction <= 1.0:
            raise ValueError("max_bad_fraction must be in [0, 1]")
        self.max_bad_fraction = float(max_bad_fraction)
        self.min_records = int(min_records)
        self.manifest_path = manifest_path
        self.label = label
        self.records: List[Dict[str, Any]] = []
        self.bad_count = 0
        self.ok_count = 0
        self._keys: set = set()
        self._lock = TracedLock("quarantine")

    # -- accounting --------------------------------------------------------
    def record_ok(self, n: int = 1) -> None:
        """Count ``n`` good records (called by the ingest path that can
        also see bad ones, so the fraction's denominator is honest)."""
        with self._lock:
            self.ok_count += int(n)

    def quarantine(self, source: str, reason: str,
                   site: str = "ingest.decode") -> None:
        """Quarantine one bad record, then enforce the budget.

        Idempotent per ``source``: a replayed pass (checkpoint resume,
        second epoch) re-hitting the same record does not double-count.
        """
        entry = {"source": str(source), "reason": str(reason),
                 "site": site}
        with self._lock:
            if entry["source"] in self._keys:
                return
            self._keys.add(entry["source"])
            self.bad_count += 1
            self.records.append(entry)
            if len(self.records) > self.MANIFEST_TAIL:
                del self.records[: len(self.records) - self.MANIFEST_TAIL]
            # the JSONL append stays INSIDE the lock: concurrent decode
            # workers must not interleave lines, and a checkpoint's
            # state() snapshot must never lead the durable manifest
            if self.manifest_path:
                try:
                    with open(self.manifest_path, "a") as f:
                        f.write(json.dumps(entry) + "\n")
                except OSError as exc:
                    # a full/unwritable manifest disk must not kill the
                    # fit; the in-memory manifest and metrics still
                    # hold the record
                    import logging

                    logging.getLogger(__name__).warning(
                        "quarantine manifest %s unwritable (%s); entry "
                        "kept in memory only", self.manifest_path, exc)
            violation = self._budget_violation(entry["source"])
        # event + raise happen outside the lock (record_event feeds the
        # metrics/trace layers — keeping the quarantine lock leaf-level
        # keeps the static lock-order graph acyclic)
        record_event("quarantine", **entry)
        if violation is not None:
            raise QuarantineBudgetExceededError(violation)

    # -- budget ------------------------------------------------------------
    def seen(self) -> int:
        with self._lock:
            return self.bad_count + self.ok_count

    def bad_fraction(self) -> float:
        with self._lock:
            return self.bad_count / max(self.bad_count + self.ok_count, 1)

    def _budget_violation(self, last_source: Optional[str] = None
                          ) -> Optional[str]:
        """Violation message, or None — caller must hold ``_lock`` (the
        counts are read together; an unlocked read could pair a new
        bad_count with a stale ok_count and trip a budget that holds)."""
        seen = self.bad_count + self.ok_count
        allowed = self.max_bad_fraction * max(seen, self.min_records)
        if self.bad_count <= allowed:
            return None
        return (
            f"{self.label}: {self.bad_count} corrupt record(s) out of "
            f"{seen} seen exceeds the quarantine budget "
            f"(max_bad_fraction={self.max_bad_fraction:g}, "
            f"min_records={self.min_records}). Last quarantined "
            f"source: {last_source or (self.records[-1]['source'] if self.records else '?')}. "
            "The data is worse than the budget allows — fix the "
            "source or raise max_bad_fraction explicitly.")

    def check_budget(self, last_source: Optional[str] = None) -> None:
        with self._lock:
            violation = self._budget_violation(last_source)
        if violation is not None:
            raise QuarantineBudgetExceededError(violation)

    # -- checkpoint state --------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """Snapshot for a streaming-fit checkpoint: the bad-record
        manifest and keys (ok counts are NOT persisted — a resume
        replays the stream from the start, recounting good records)."""
        with self._lock:
            return {"records": list(self.records),
                    "keys": sorted(self.records and self._keys or ()),
                    "bad_count": self.bad_count}

    def restore(self, state: Dict[str, Any]) -> None:
        """Restore a :meth:`state` snapshot (checkpoint resume). Good
        counts reset to zero: the replay re-decodes every record, so
        they recount naturally while restored bad keys dedupe."""
        with self._lock:
            self.records = list(state.get("records", ()))
            self._keys = set(state.get("keys", ()))
            self.bad_count = int(state.get("bad_count", len(self.records)))
            self.ok_count = 0

    def summary(self) -> str:
        return (f"quarantine[{self.label}]: {self.bad_count} bad / "
                f"{self.seen()} seen "
                f"(budget {self.max_bad_fraction:g})")

    def quarantined_keys(self) -> set:
        """The source identities quarantined so far (a snapshot)."""
        with self._lock:
            return set(self._keys)


def drop_quarantined_rows(labels: Any, record_keys: Any,
                          quarantine: "Quarantine") -> Any:
    """Align resident labels with a quarantine-shrunk stream.

    Quarantined records are SKIPPED by the ingest path, so a stream
    backed by a tar with corrupt members yields fewer rows than labels
    sized for the full record count — and ``fit_streaming`` then
    (correctly) refuses with its misalignment error rather than
    silently truncating, because nothing says WHICH rows went missing.
    This helper says which: given the per-record source identities in
    stream order (``record_keys``, e.g. ``f"{tar}::{member}"`` for
    every member the labels were built for), it drops exactly the label
    rows whose key sits in the quarantine manifest.

    ``labels`` is a numpy-like ``(n, ...)`` array (or anything
    ``np.asarray`` accepts) with one row per entry of ``record_keys``;
    the return value keeps only rows whose record decoded::

        stream = stream_tar_images([tar], chunk_size)
        rows = sum(c.n for c in stream.chunks())   # quarantine filled
        y = drop_quarantined_rows(y_full, keys, stream.quarantine)
        model = fit_streaming(est, stream, y, quarantine=stream.quarantine)

    The quarantine must already hold the bad records (run one pass, or
    reuse a manifest restored via :meth:`Quarantine.restore`) — this is
    a pure row filter, it never decodes anything itself.
    """
    import numpy as np

    arr = np.asarray(labels)
    keys = [str(k) for k in record_keys]
    if arr.shape[0] != len(keys):
        raise ValueError(
            f"labels have {arr.shape[0]} rows but {len(keys)} record "
            "keys were given — record_keys must name every record the "
            "labels were built for, in stream order")
    bad = quarantine.quarantined_keys()
    keep = np.array([k not in bad for k in keys], dtype=bool)
    return arr[keep]
