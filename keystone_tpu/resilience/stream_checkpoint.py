"""Checkpoint/resume for streaming fits.

The reference leaned on Spark lineage + optional RDD checkpointing: a
lost executor recomputed its partitions, a checkpointed RDD restarted
from disk. A streamed TPU fit has exactly one piece of evolving state —
the estimator carry (Gram/cross/moments) plus the chunk cursor — so
checkpointing it is cheap (O(d*(d+k)), not O(n)) and resume is exact:

* :class:`StreamCheckpoint` atomically snapshots ``(format version,
  config fingerprint, chunk cursor, carry, quarantine state)`` via
  temp-file + ``os.replace`` every N chunks;
* a resumed ``fit_streaming`` replays the source, SKIPS accumulation
  for the first ``cursor`` chunks (they are already folded into the
  restored carry), and continues — the remaining accumulate ops see
  bit-identical inputs in the same order, so the resumed weights are
  bit-comparable with an uninterrupted run (f32 host round-trip of the
  carry is exact);
* the **fingerprint** binds the snapshot to (estimator config, chunk
  geometry, labels kind): resuming under ANY change raises
  :class:`CheckpointMismatchError` instead of silently folding new
  chunks into a stale carry.

**Distributed mode** (:mod:`keystone_tpu.parallel.distributed`): an
N-process streamed fit checkpoints as one WORLD snapshot in a shared
directory — each host atomically writes a per-host sidecar (its own
cursor, carry, quarantine and drift-sketch state) at a coordination
round boundary, a barrier makes every sidecar durable, then host 0
folds them into the world snapshot (``save_host`` / ``merge_hosts`` /
``load_world``). The snapshot records the process TOPOLOGY, and the
fingerprint folds it too: a relaunched world resumes only at the SAME
world size — a 2-host snapshot loaded by a 4-host (or single-process)
fit raises :class:`CheckpointMismatchError` naming both sizes, because
per-host cursors are meaningless under a different shard partition.

Truncated/corrupt snapshot files raise :class:`CheckpointCorruptError`
(shared with :mod:`keystone_tpu.utils.checkpoint`) naming the path.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
from typing import Any, Dict, Optional

import numpy as np

from .events import record_event


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but cannot be read back (truncated
    write, bad bytes, wrong format/version). The message names the
    path; deleting the file starts clean."""


class CheckpointMismatchError(RuntimeError):
    """A checkpoint's config fingerprint does not match the current fit
    — refusing to resume from another pipeline's state."""


def atomic_pickle_dump(payload: Any, path: str) -> None:
    """THE atomic checkpoint write (shared by this module,
    ``utils.checkpoint`` and the solver checkpoint): pickle to a
    pid-suffixed temp file, then ``os.replace`` — a crash mid-write
    leaves the previous artifact intact, never a torn file, and two
    local runs cannot clobber each other's in-flight temp."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)


# -- config fingerprint ------------------------------------------------------

def _stable(obj: Any) -> Any:
    """JSON-able, address-free view of a config value: callables map to
    their qualified name, arrays to their shape/dtype, everything else
    to repr with memory addresses stripped (so the fingerprint is
    stable across processes — the whole point of resume)."""
    if isinstance(obj, (str, int, float, bool, type(None))):
        return obj
    if isinstance(obj, type):
        return f"type:{obj.__module__}.{obj.__qualname__}"
    if callable(obj) and hasattr(obj, "__qualname__"):
        return f"fn:{getattr(obj, '__module__', '?')}.{obj.__qualname__}"
    if isinstance(obj, (list, tuple)):
        return [_stable(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _stable(v) for k, v in sorted(obj.items(),
                                                      key=lambda kv:
                                                      str(kv[0]))}
    if isinstance(obj, np.ndarray):
        return f"ndarray{tuple(obj.shape)}:{obj.dtype}"
    return re.sub(r"0x[0-9a-fA-F]+", "0x", repr(obj))


def _estimator_key(estimator: Any) -> Any:
    eq = getattr(estimator, "eq_key", None)
    if callable(eq):
        try:
            return _stable(eq())
        except TypeError:
            pass  # eq_key needing arguments: fall through to config
    cfg = {k: v for k, v in vars(estimator).items()
           if not k.startswith("_")}
    return [f"{type(estimator).__module__}.{type(estimator).__qualname__}",
            _stable(cfg)]


def fit_fingerprint(estimator: Any, data: Any,
                    labels: Any = None) -> str:
    """Stable id of one streamed-fit configuration: the estimator's
    config, the stream's padded chunk geometry + source tag + wire/
    compute dtype policy, and the labels — resident labels by a CONTENT
    digest (they are host-side
    and k-wide, so hashing them is cheap and catches "same shape,
    different labels"), streamed labels by chunk geometry.
    ``prefetch_depth`` and retry/watchdog settings are deliberately
    excluded: they change scheduling, not results, so a resume may
    tune them.

    Under a live ``jax.distributed`` world the PROCESS TOPOLOGY is
    part of the identity too: each host's snapshot cursor counts ITS
    shard's chunks, so a resume at a different world size would replay
    a different partition of the data against a carry accumulated
    under the old one. The world size folds in here (and
    ``StreamCheckpoint.load_world`` additionally checks the recorded
    topology explicitly, so the refusal names both sizes).

    Honest limit: the fingerprint cannot see STREAM content without
    consuming the stream. Swapping the records behind an identical
    source tag / chunk size (or behind streamed labels) between kill
    and resume is not detectable here — keep the source stable across
    a resume, as you would for any replay-based recovery."""
    if labels is None:
        labels_key: Any = None
    elif hasattr(labels, "chunk_size") and hasattr(labels, "chunks"):
        # the labels stream's wire/compute policy is numeric identity
        # too — resuming under a reconfigured labels wire would mix
        # quantizations in the carry exactly like the data-side case
        lw = getattr(labels, "wire_dtype_name", None)
        lc = getattr(labels, "compute_dtype_name", None)
        labels_key = (f"stream:chunk_size={labels.chunk_size}:"
                      f"wire={lw() if callable(lw) else None}:"
                      f"compute={lc() if callable(lc) else None}")
    else:
        from ..parallel.dataset import to_numpy

        arr = np.ascontiguousarray(to_numpy(labels))
        digest = hashlib.sha256(arr.tobytes()).hexdigest()[:16]
        labels_key = (f"resident:{tuple(arr.shape)}:{arr.dtype}:"
                      f"{digest}")
    element = getattr(data, "element", None)

    def _policy_name(attr: str) -> Optional[str]:
        name = getattr(data, attr, None)
        return name() if callable(name) else None

    parts = {
        "estimator": _estimator_key(estimator),
        "chunk_size": int(getattr(data, "chunk_size", 0)),
        "data_tag": getattr(data, "tag", None),
        "data_element": _stable(element() if callable(element) else None),
        # the wire/compute dtype policy is part of the NUMERIC identity
        # of a streamed fit: a checkpoint written under a uint8 wire
        # must refuse to resume a run reconfigured to an f32 wire (the
        # narrowing quantizes values — silently mixing the two carries
        # would drift the weights with no error anywhere); the name
        # methods serialize pytree (per-leaf) policies too
        "wire_dtype": _policy_name("wire_dtype_name"),
        "compute_dtype": _policy_name("compute_dtype_name"),
        "labels": labels_key,
    }
    from ..parallel.distributed import process_count

    if process_count() > 1:
        parts["topology"] = {"processes": process_count()}
    blob = json.dumps(parts, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# -- the snapshot file -------------------------------------------------------

class StreamCheckpoint:
    """Atomic snapshot/restore of one streaming fit's progress."""

    MAGIC = "keystone-stream-fit"
    VERSION = 1

    def __init__(self, directory: str, name: str = "stream_fit"):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"{name}.ckpt")

    def save(self, fingerprint: str, cursor: int, carry: Any,
             quarantine_state: Optional[Dict[str, Any]] = None,
             numerics: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot after chunk ``cursor - 1``: carry leaves move to
        host (blocks on the device result — the checkpoint must not
        capture an in-flight accumulation) and the file replaces the
        previous snapshot atomically, so a kill mid-write leaves the
        LAST complete snapshot, never a torn one.

        ``numerics`` is the drift-sketch state
        (``observability.numerics.SketchTracker.state()``): it rides
        the snapshot so a resumed fit's drift baseline is bit-identical
        with an uninterrupted one. Optional and absent from older
        snapshots — ``load`` hands back whatever the file holds."""
        import jax

        host_carry = jax.tree_util.tree_map(np.asarray, carry)
        atomic_pickle_dump({
            "magic": self.MAGIC, "version": self.VERSION,
            "fingerprint": fingerprint, "cursor": int(cursor),
            "carry": host_carry, "quarantine": quarantine_state,
            "numerics": numerics,
        }, self.path)
        record_event("checkpoint_save", path=self.path, cursor=int(cursor))

    def _read_blob(self, path: str) -> Dict[str, Any]:
        """Read + format-validate one snapshot/sidecar file (shared by
        the single-process and world load paths)."""
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
        except Exception as exc:
            raise CheckpointCorruptError(
                f"stream checkpoint {path!r} is truncated or "
                f"corrupt ({type(exc).__name__}: {exc}); delete it to "
                "start the fit from scratch") from exc
        if not (isinstance(blob, dict) and blob.get("magic") == self.MAGIC):
            raise CheckpointCorruptError(
                f"{path!r} is not a keystone stream checkpoint "
                "(missing format header); delete it to start over")
        if blob.get("version") != self.VERSION:
            raise CheckpointCorruptError(
                f"stream checkpoint {path!r} has format version "
                f"{blob.get('version')!r}, this build reads "
                f"{self.VERSION}; delete it to start over")
        return blob

    def load(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        """The last snapshot, or None when none exists. Corrupt files
        raise :class:`CheckpointCorruptError`; a fingerprint mismatch
        raises :class:`CheckpointMismatchError` (never silently refits
        or resumes wrong state)."""
        if not os.path.exists(self.path):
            return None
        blob = self._read_blob(self.path)
        topo = blob.get("topology")
        if topo is not None:
            raise CheckpointMismatchError(
                f"stream checkpoint {self.path!r} was written by a "
                f"{topo.get('processes')}-process world; a "
                "single-process fit cannot resume it — per-host "
                "cursors only make sense under the original shard "
                "partition. Relaunch at world size "
                f"{topo.get('processes')} (CLUSTER.md 'Elastic "
                "resume'), or delete the checkpoint directory to "
                "start over")
        if blob.get("fingerprint") != fingerprint:
            raise CheckpointMismatchError(
                f"stream checkpoint {self.path!r} was written by a "
                f"different fit configuration (fingerprint "
                f"{blob.get('fingerprint')!r} != {fingerprint!r}); "
                "refusing to resume. Delete the checkpoint directory "
                "to start over, or restore the original estimator/"
                "chunk-size/labels configuration")
        record_event("checkpoint_restore", path=self.path,
                     cursor=int(blob["cursor"]))
        return blob

    # -- distributed (world) snapshots -------------------------------------
    def host_path(self, process_id: int) -> str:
        """This host's sidecar file (same directory as the world
        snapshot — the directory must be shared storage, which the
        resume contract requires anyway)."""
        base, ext = os.path.splitext(self.path)
        return f"{base}.host{int(process_id)}{ext}"

    def save_host(self, fingerprint: str, process_id: int, cursor: int,
                  carry: Any,
                  quarantine_state: Optional[Dict[str, Any]] = None,
                  numerics: Optional[Dict[str, Any]] = None) -> None:
        """One host's contribution to a coordinated snapshot: cursor +
        carry + quarantine/drift state, written atomically to the
        host's sidecar. The caller (the distributed ``fit_streaming``
        round loop) barriers after every host has written, then host 0
        folds the sidecars via :meth:`merge_hosts` — so the world
        snapshot is always a CONSISTENT cut at a round boundary."""
        import jax

        host_carry = jax.tree_util.tree_map(np.asarray, carry)
        atomic_pickle_dump({
            "magic": self.MAGIC, "version": self.VERSION,
            "fingerprint": fingerprint, "process_id": int(process_id),
            "cursor": int(cursor), "carry": host_carry,
            "quarantine": quarantine_state, "numerics": numerics,
        }, self.host_path(process_id))
        record_event("checkpoint_save", path=self.host_path(process_id),
                     cursor=int(cursor))

    def merge_hosts(self, processes: int) -> None:
        """Fold every host sidecar into THE world snapshot (host 0
        only, after the sidecar barrier). The snapshot holds per-host
        cursors/carries/quarantine manifests plus the topology, so a
        relaunched world restores each host's exact position — and a
        DIFFERENT world size is refused before any state is touched."""
        hosts = []
        for p in range(int(processes)):
            blob = self._read_blob(self.host_path(p))
            hosts.append({k: blob.get(k) for k in
                          ("fingerprint", "cursor", "carry", "quarantine",
                           "numerics")})
        atomic_pickle_dump({
            "magic": self.MAGIC, "version": self.VERSION,
            # no world-level fingerprint: hosts may legitimately differ
            # (per-shard source tags), so identity is checked per host
            # slice at load_world — a derived digest here would imply a
            # cross-host-consistency check that doesn't exist
            "topology": {"processes": int(processes)},
            "hosts": hosts,
        }, self.path)
        record_event("checkpoint_save", path=self.path,
                     cursor=min(int(h["cursor"]) for h in hosts),
                     world=int(processes))

    def load_world(self, fingerprint: str, process_id: int,
                   processes: int) -> Optional[Dict[str, Any]]:
        """This host's slice of the last world snapshot, or None when
        none exists. Topology is checked FIRST: a snapshot from a
        different world size (including a single-process one) raises
        :class:`CheckpointMismatchError` naming both sizes."""
        if not os.path.exists(self.path):
            return None
        blob = self._read_blob(self.path)
        topo = blob.get("topology")
        if topo is None:
            raise CheckpointMismatchError(
                f"stream checkpoint {self.path!r} was written by a "
                f"single-process fit; a {int(processes)}-process world "
                "cannot resume it — the shard partition differs. "
                "Relaunch single-process, or delete the checkpoint "
                "directory to start over")
        if int(topo.get("processes", -1)) != int(processes):
            raise CheckpointMismatchError(
                f"stream checkpoint {self.path!r} was written by a "
                f"{topo.get('processes')}-process world but this world "
                f"has {int(processes)} processes; refusing to resume — "
                "per-host cursors are only meaningful under the "
                "original shard partition. Relaunch at world size "
                f"{topo.get('processes')}, or delete the checkpoint "
                "directory to start over (CLUSTER.md 'Elastic resume')")
        host = blob["hosts"][int(process_id)]
        if host.get("fingerprint") != fingerprint:
            raise CheckpointMismatchError(
                f"stream checkpoint {self.path!r} (host {process_id} "
                f"slice) was written by a different fit configuration "
                f"(fingerprint {host.get('fingerprint')!r} != "
                f"{fingerprint!r}); refusing to resume. Delete the "
                "checkpoint directory to start over, or restore the "
                "original estimator/chunk-size/labels configuration")
        record_event("checkpoint_restore", path=self.path,
                     cursor=int(host["cursor"]))
        return dict(host)

    def clear(self) -> None:
        """Remove the snapshot (and any host sidecars) after a
        successful finalize (a stale snapshot must never seed an
        unrelated later fit)."""
        import glob

        base, ext = os.path.splitext(self.path)
        for path in [self.path] + glob.glob(f"{base}.host*{ext}"):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
