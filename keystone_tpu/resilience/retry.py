"""Retry with exponential backoff + jitter, and the ingest watchdog error.

The reference framework got task retry for free from Spark (a flaky
disk read killed one task, the scheduler reran it); the TPU port runs
its ingest on bare threads, so one transient I/O error previously
killed a multi-hour streamed fit. :class:`RetryPolicy` is the in-tree
replacement, applied at the record level (tar reads, image decodes) and
the chunk level (``device_put`` staging in the prefetcher):

* **classification** — only :meth:`RetryPolicy.is_retryable` exceptions
  are retried. Transient things (``TransientError``, timeouts, generic
  ``OSError``) are; deterministic ones (missing file, permission,
  :class:`~keystone_tpu.resilience.quarantine.CorruptRecordError`) are
  not — retrying a corrupt JPEG three times just burns backoff time.
* **exponential backoff + seeded jitter** — ``backoff_s * multiplier^i``
  capped at ``max_backoff_s``, stretched by up to ``jitter`` uniform
  randomness from a seeded RNG (deterministic under the fault harness).
* **per-attempt timeout** — with ``attempt_timeout_s`` set, an attempt
  runs on a helper thread and is abandoned (counted as a transient
  failure) when it overruns. The abandoned thread is daemonic and may
  linger until its blocking call returns; use only around calls that do
  eventually return.

Every retry feeds ``resilience.retry`` metrics and the active trace
(:mod:`.events`); exhaustion raises :class:`RetryExhaustedError` with
the final failure as ``__cause__``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Tuple, Type

import numpy as np

from ..utils.guarded import TracedLock
from .events import record_event
from .quarantine import CorruptRecordError


class TransientError(Exception):
    """Base class for failures that are worth retrying."""


class AttemptTimeoutError(TransientError):
    """An attempt overran its per-attempt timeout (counts as transient:
    the next attempt may be served from a recovered disk/device)."""


class RetryExhaustedError(RuntimeError):
    """Every attempt failed; ``__cause__`` is the last failure."""

    def __init__(self, site: str, attempts: int,
                 last: BaseException):
        super().__init__(
            f"{site}: all {attempts} attempt(s) failed; last error: "
            f"{type(last).__name__}: {last}")
        self.site = site
        self.attempts = attempts


class IngestTimeoutError(RuntimeError):
    """The streaming consumer's producer watchdog tripped: the source
    produced no chunk within its deadline (hung disk/decoder/producer).
    Raised instead of blocking the fit forever.

    Defaults, for the operator reading this out of a post-mortem:
    ``stall_timeout_s`` on :class:`~keystone_tpu.parallel.streaming.\
StreamingDataset` defaults to **None = no deadline** — a hung-but-alive
    source blocks like a plain queue (a DEAD producer thread still
    raises immediately, deadline or not). Set it to ~10x the worst
    healthy inter-chunk gap (the ``streaming.ingest_stall_s`` histogram
    p99 is the evidence) to convert hangs into this error; the retry
    layer's own per-attempt knob is ``attempt_timeout_s`` (also default
    None) on the :class:`RetryPolicy` printed in the message."""


#: worth retrying by default: explicit transients, timeouts, and generic
#: I/O errors (a flaky NFS read raises plain OSError)
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    TransientError, TimeoutError, InterruptedError, ConnectionError,
    OSError)

#: never retried even though they subclass a retryable type: these are
#: deterministic — the retry would fail identically three times, slower
DEFAULT_NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, PermissionError, IsADirectoryError,
    NotADirectoryError, CorruptRecordError)


class RetryPolicy:
    """Configurable retry/backoff; see module docstring.

    One policy instance may be shared across threads (the tar decode
    pool retries records concurrently): the jitter RNG is guarded by a
    lock, everything else is immutable.
    """

    def __init__(self, max_attempts: int = 3, backoff_s: float = 0.05,
                 multiplier: float = 2.0, max_backoff_s: float = 2.0,
                 jitter: float = 0.5,
                 attempt_timeout_s: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...]
                 = DEFAULT_RETRYABLE,
                 non_retryable: Tuple[Type[BaseException], ...]
                 = DEFAULT_NON_RETRYABLE,
                 seed: int = 0):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.attempt_timeout_s = attempt_timeout_s
        self.retryable = tuple(retryable)
        self.non_retryable = tuple(non_retryable)
        # the jitter RNG draws concurrently from decode-pool threads;
        # guarded (utils.guarded.GUARDED_FIELDS declares _rng -> _lock)
        self._rng = np.random.RandomState(seed)
        self._lock = TracedLock("retry.jitter")

    def __repr__(self) -> str:
        """One line naming the policy in force — post-mortems and logs
        print retry policies, and an opaque ``<RetryPolicy object at
        0x...>`` tells an operator nothing about why a fit waited
        ~``backoff_s * multiplier^k`` between failures."""
        timeout = ("none" if self.attempt_timeout_s is None
                   else f"{self.attempt_timeout_s:g}s")
        return (f"RetryPolicy(attempts={self.max_attempts}, "
                f"backoff={self.backoff_s:g}s*{self.multiplier:g}^k"
                f"<={self.max_backoff_s:g}s, jitter={self.jitter:g}, "
                f"attempt_timeout={timeout})")

    # -- classification ----------------------------------------------------
    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable) and not isinstance(
            exc, self.non_retryable)

    # -- backoff -----------------------------------------------------------
    def backoff(self, attempt: int) -> float:
        """Sleep before attempt ``attempt + 1`` (``attempt`` is
        1-based): exponential base stretched by seeded jitter."""
        base = min(self.backoff_s * self.multiplier ** (attempt - 1),
                   self.max_backoff_s)
        with self._lock:
            j = float(self._rng.rand())
        return base * (1.0 + self.jitter * j)

    # -- the driver --------------------------------------------------------
    def _attempt(self, fn: Callable[..., Any], args, kwargs) -> Any:
        if self.attempt_timeout_s is None:
            return fn(*args, **kwargs)
        done = threading.Event()
        box: list = []

        def run():
            try:
                box.append(("ok", fn(*args, **kwargs)))
            except BaseException as exc:
                box.append(("err", exc))
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="keystone-retry-attempt")
        t.start()
        if not done.wait(self.attempt_timeout_s):
            raise AttemptTimeoutError(
                f"attempt exceeded {self.attempt_timeout_s:g}s "
                "(abandoned; counted as transient)")
        kind, value = box[0]
        if kind == "err":
            raise value
        return value

    def call(self, fn: Callable[..., Any], *args: Any,
             site: str = "retry", **kwargs: Any) -> Any:
        """Run ``fn`` under the policy. Non-retryable exceptions
        propagate unchanged on the first failure; retryable ones are
        retried with backoff and finally wrapped in
        :class:`RetryExhaustedError` (``__cause__`` = last failure)."""
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return self._attempt(fn, args, kwargs)
            except BaseException as exc:
                if not self.is_retryable(exc):
                    raise
                last = exc
                record_event("retry", site=site, attempt=attempt,
                             error=f"{type(exc).__name__}: {exc}")
                if attempt < self.max_attempts:
                    time.sleep(self.backoff(attempt))
        record_event("retry_exhausted", site=site,
                     attempts=self.max_attempts,
                     error=f"{type(last).__name__}: {last}")
        from ..observability.postmortem import attach_postmortem

        # the dump carries every retry's instant event plus the ingest
        # spans around them — the difference between "it failed" and
        # "the third attempt timed out mid-stage while the pool drained"
        raise attach_postmortem(
            RetryExhaustedError(site, self.max_attempts, last),
            "retry_exhausted",
            {"site": site, "attempts": self.max_attempts,
             "last_error": f"{type(last).__name__}: {last}",
             # the one-line policy identity: which knobs were in force
             "policy": repr(self)}) from last


#: shared default policy: 3 attempts, 50 ms base backoff. Module-level
#: so every ingest site that is not given an explicit policy shares one
#: jitter RNG (deterministic under a fixed seed) and zero per-call
#: construction cost.
_DEFAULT_POLICY: Optional[RetryPolicy] = None
_DEFAULT_POLICY_LOCK = threading.Lock()


def default_retry_policy() -> RetryPolicy:
    # double-checked: two loader threads racing the first build would
    # otherwise each keep a policy, splitting the shared jitter RNG's
    # deterministic sequence in two (the lazy-init double-create shape
    # the concurrency passes hunt)
    global _DEFAULT_POLICY
    policy = _DEFAULT_POLICY
    if policy is None:
        with _DEFAULT_POLICY_LOCK:
            policy = _DEFAULT_POLICY
            if policy is None:
                policy = _DEFAULT_POLICY = RetryPolicy()
    return policy
