"""Seeded, deterministic fault injection at named sites.

The resilience layer's guarantees (retry absorbs transients, corrupt
records quarantine, a hung producer trips the watchdog, a killed fit
resumes) are worth nothing asserted — they need tests that exercise the
REAL code paths. The ingest/staging code therefore calls
:func:`inject` at named sites:

====================  =====================================================
site                  where
====================  =====================================================
``ingest.read``       per tar-member raw read (``loaders._iter_tar_entries``)
``ingest.decode``     per image decode attempt (tar decode pool)
``ingest.stage``      per chunk ``device_put`` staging (prefetcher)
``ingest.produce``    per chunk in the prefetch producer loop
====================  =====================================================

``inject`` is a single global read when no plan is active — zero cost
in production. Under ``with FaultPlan(seed) as plan:`` each visit to a
site consults the plan's specs:

* ``kind="error"`` raises (default
  :class:`~keystone_tpu.resilience.retry.TransientError`; pass
  ``error=`` for corrupt-record or fatal flavors),
* ``kind="latency"`` sleeps ``delay_s`` (an I/O latency spike),
* ``kind="hang"`` blocks until the plan exits, the caller's ``abort``
  callback goes true, or ``delay_s`` elapses — a hung producer,
* ``kind="corrupt"`` MUTATES the value flowing through a
  value-carrying site (:func:`corrupt`, wired at ``ingest.stage`` in
  the streaming prefetcher): the default mutation poisons the first
  element of the first float leaf with NaN — the exact "NaN born in
  chunk k" failure the numerics tripwire
  (:mod:`keystone_tpu.observability.numerics`) exists to catch; pass
  ``mutate=`` for other corruptions.

Injection is deterministic: ``rate`` draws come from the plan's seeded
RNG, and ``after``/``count`` give exact "fail once, after the k-th
visit" placement (the kill-and-resume tests are built on this).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.guarded import TracedLock
from .events import record_event
from .retry import TransientError


class InjectedFaultError(TransientError):
    """Default injected failure: transient, so the retry path absorbs
    it. Pass ``error=`` to :meth:`FaultPlan.add` for other flavors."""


@dataclass
class FaultSpec:
    """One injection rule at one site."""

    site: str
    kind: str = "error"          # error | latency | hang | corrupt
    rate: float = 1.0            # per-visit injection probability
    after: int = 0               # skip the first `after` visits entirely
    count: Optional[int] = None  # at most this many injections
    error: Optional[Callable[[str], BaseException]] = None
    delay_s: float = 0.05        # latency duration / hang cap
    mutate: Optional[Callable[[Any], Any]] = None  # corrupt transform
    visits: int = field(default=0, compare=False)
    injected: int = field(default=0, compare=False)


def _poison_nan(value: Any) -> Any:
    """Default ``kind="corrupt"`` mutation: NaN into the first element
    of the first FLOAT leaf (copies the leaf — sources may hand out
    views of long-lived host buffers). Integer-only values pass through
    unchanged: an integer wire cannot carry NaN, which is also why the
    numerics gate streams f32."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(value)
    out = []
    poisoned = False
    for leaf in leaves:
        arr = np.asarray(leaf)
        if not poisoned and np.issubdtype(arr.dtype, np.floating) \
                and arr.size:
            arr = arr.copy()
            arr.reshape(-1)[0] = np.nan
            poisoned = True
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


_ACTIVE: Optional["FaultPlan"] = None


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules, active inside ``with``.

    Usage::

        plan = (FaultPlan(seed=7)
                .add("ingest.decode", rate=0.1)             # transient
                .add("ingest.produce", kind="hang", after=3, count=1))
        with plan:
            fit_streaming(est, stream, labels)
        assert plan.injections("ingest.decode") > 0
    """

    def __init__(self, seed: int = 0):
        # specs/log/rng are hit from every instrumented ingest thread;
        # guarded (utils.guarded.GUARDED_FIELDS declares the fields)
        self._rng = np.random.RandomState(seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._lock = TracedLock("faults")
        self._release = threading.Event()
        self.log: List[Dict[str, Any]] = []

    def add(self, site: str, kind: str = "error", rate: float = 1.0,
            after: int = 0, count: Optional[int] = None,
            error: Optional[Callable[[str], BaseException]] = None,
            delay_s: float = 0.05,
            mutate: Optional[Callable[[Any], Any]] = None) -> "FaultPlan":
        if kind not in ("error", "latency", "hang", "corrupt"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if not 0.0 < rate <= 1.0:
            raise ValueError("rate must be in (0, 1]")
        spec = FaultSpec(site=site, kind=kind, rate=rate, after=int(after),
                         count=count, error=error, delay_s=float(delay_s),
                         mutate=mutate)
        self._specs.setdefault(site, []).append(spec)
        return self

    # -- activation --------------------------------------------------------
    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("another FaultPlan is already active")
        self._release.clear()
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None
        # wake every hung site so daemon threads blocked in a "hang"
        # injection finish instead of lingering for delay_s
        self._release.set()

    # -- introspection -----------------------------------------------------
    def injections(self, site: Optional[str] = None) -> int:
        with self._lock:
            return len([e for e in self.log
                        if site is None or e["site"] == site])

    # -- the injection point ----------------------------------------------
    def fire(self, site: str, context: Any,
             abort: Optional[Callable[[], bool]] = None) -> None:
        specs = self._specs.get(site)
        if not specs:
            return
        for spec in specs:
            if spec.kind == "corrupt":
                continue  # value-carrying rule: fires via corrupt()
            with self._lock:
                spec.visits += 1
                if spec.visits <= spec.after:
                    continue
                if spec.count is not None and spec.injected >= spec.count:
                    continue
                if spec.rate < 1.0 and float(self._rng.rand()) >= spec.rate:
                    continue
                spec.injected += 1
                self.log.append({"site": site, "kind": spec.kind,
                                 "context": context})
            record_event("fault_injected", site=site, kind=spec.kind,
                         context=str(context))
            if spec.kind == "latency":
                time.sleep(spec.delay_s)
            elif spec.kind == "hang":
                deadline = time.perf_counter() + spec.delay_s
                while (not self._release.wait(0.02)
                       and not (abort is not None and abort())
                       and time.perf_counter() < deadline):
                    pass
            else:
                exc = (spec.error(f"injected fault at {site} ({context})")
                       if spec.error is not None else
                       InjectedFaultError(
                           f"injected fault at {site} ({context})"))
                raise exc


    def mutate_value(self, site: str, value: Any, context: Any) -> Any:
        """Apply this plan's ``kind="corrupt"`` rules at a
        value-carrying site (same visit/after/count/rate gating as
        :meth:`fire`, same seeded RNG)."""
        specs = self._specs.get(site)
        if not specs:
            return value
        for spec in specs:
            if spec.kind != "corrupt":
                continue
            with self._lock:
                spec.visits += 1
                if spec.visits <= spec.after:
                    continue
                if spec.count is not None and spec.injected >= spec.count:
                    continue
                if spec.rate < 1.0 and float(self._rng.rand()) >= spec.rate:
                    continue
                spec.injected += 1
                self.log.append({"site": site, "kind": "corrupt",
                                 "context": context})
            record_event("fault_injected", site=site, kind="corrupt",
                         context=str(context))
            value = (spec.mutate or _poison_nan)(value)
        return value


def inject(site: str, context: Any = None,
           abort: Optional[Callable[[], bool]] = None) -> None:
    """The per-site hook: a no-op (one global read) unless a
    :class:`FaultPlan` is active. ``abort`` lets long "hang" injections
    end early when the caller is shutting down (the stream producer
    passes its stop event)."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(site, context, abort)


def corrupt(site: str, value: Any, context: Any = None) -> Any:
    """The value-carrying injection hook (``kind="corrupt"`` rules):
    returns ``value`` untouched — one global read — unless an active
    plan has a corrupt rule at ``site``. Wired at the streaming
    ``ingest.stage`` site (the host chunk, BEFORE any wire narrowing,
    so a poisoned NaN actually survives to the device)."""
    plan = _ACTIVE
    if plan is None:
        return value
    return plan.mutate_value(site, value, context)
