"""Seeded, deterministic fault injection at named sites.

The resilience layer's guarantees (retry absorbs transients, corrupt
records quarantine, a hung producer trips the watchdog, a killed fit
resumes) are worth nothing asserted — they need tests that exercise the
REAL code paths. The ingest/staging code therefore calls
:func:`inject` at named sites:

====================  =====================================================
site                  where
====================  =====================================================
``ingest.read``       per tar-member raw read (``loaders._iter_tar_entries``)
``ingest.decode``     per image decode attempt (tar decode pool)
``ingest.stage``      per chunk ``device_put`` staging (prefetcher)
``ingest.produce``    per chunk in the prefetch producer loop
``coord.step``        per cross-host coordination round
                      (``parallel.distributed.WorldCoordinator.step``)
``serve.enqueue``     per serving request submit, before the slot gate
                      (``serving.batcher.MicroBatcher.submit_request``)
``serve.dispatch``    per micro-batch device dispatch
                      (``serving.plane.ServingPlane._serve_batch``) —
                      a ``straggler`` here is the slow-batch tail the
                      SLO gate trips on; a ``corrupt`` rule poisons the
                      MERGED batch value pre-dispatch (the plane's
                      nonfinite guard must classify it, not serve NaN)
``serve.admit``       per admission, twice: once BEFORE any plane
                      mutation (atomic refusal) and once per warmup
                      bucket (``ServingPlane._warm`` — a mid-warmup
                      fault must roll the whole admission back)
``serve.evict``       per explicit eviction, before any mutation
                      (``ServingPlane.evict`` — eviction under fault
                      is atomic: fully done or not started)
====================  =====================================================

``inject`` is a single global read when no plan is active — zero cost
in production. Under ``with FaultPlan(seed) as plan:`` each visit to a
site consults the plan's specs:

* ``kind="error"`` raises (default
  :class:`~keystone_tpu.resilience.retry.TransientError`; pass
  ``error=`` for corrupt-record or fatal flavors),
* ``kind="latency"`` sleeps ``delay_s`` (an I/O latency spike),
* ``kind="hang"`` blocks until the plan exits, the caller's ``abort``
  callback goes true, or ``delay_s`` elapses — a hung producer,
* ``kind="corrupt"`` MUTATES the value flowing through a
  value-carrying site (:func:`corrupt`, wired at ``ingest.stage`` in
  the streaming prefetcher): the default mutation poisons the first
  element of the first float leaf with NaN — the exact "NaN born in
  chunk k" failure the numerics tripwire
  (:mod:`keystone_tpu.observability.numerics`) exists to catch; pass
  ``mutate=`` for other corruptions.

**Host-level (process-granular) kinds** — the elastic multi-host story
(:mod:`keystone_tpu.parallel.distributed`) needs faults that take out a
PROCESS, not a record. Every spec takes ``process_id=``: when set, the
rule fires only on that ``jax.process_index()`` (None = every host),
so one plan installed identically on every SPMD worker — the dryrun
harness's contract — still kills exactly one host:

* ``kind="host_death"`` hard-exits the process via ``os._exit``
  (:data:`HOST_DEATH_EXIT_CODE`) — the SIGKILL-a-host simulation the
  kill-one-host-mid-fit resume tests and ``tools/elastic_gate.py``
  are built on; nothing is flushed, exactly like a real kill,
* ``kind="partition"`` raises :class:`PartitionError` (a
  ``ConnectionError`` flavor: retryable at ingest sites, fatal at a
  coordination site — the surviving world relaunches and resumes),
* ``kind="straggler"`` sleeps ``delay_s`` (default 0.25 s) per fired
  visit — one slow host holding back every coordination barrier.

Injection is deterministic: ``rate`` draws come from the plan's seeded
RNG, and ``after``/``count`` give exact "fail once, after the k-th
visit" placement (the kill-and-resume tests are built on this).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.guarded import TracedLock
from .events import record_event
from .retry import TransientError


class InjectedFaultError(TransientError):
    """Default injected failure: transient, so the retry path absorbs
    it. Pass ``error=`` to :meth:`FaultPlan.add` for other flavors."""


class PartitionError(ConnectionError):
    """An injected network partition (``kind="partition"``): the host
    can run but cannot reach its peers. ``ConnectionError`` is in
    ``DEFAULT_RETRYABLE``, so an ingest-site partition retries like a
    flaky NFS mount; at a coordination site it kills the step and the
    world recovers by relaunch-and-resume."""


#: the exit status a ``kind="host_death"`` injection dies with — the
#: dryrun launcher and the elastic gate assert on it to distinguish a
#: deliberately killed host from an organic crash
HOST_DEATH_EXIT_CODE = 117

_KINDS = ("error", "latency", "hang", "corrupt",
          "host_death", "partition", "straggler")


def _process_index() -> int:
    """This process's SPMD index (0 when jax.distributed was never
    initialized — the single-process case)."""
    import jax

    try:
        return int(jax.process_index())
    except Exception:
        return 0


@dataclass
class FaultSpec:
    """One injection rule at one site."""

    site: str
    kind: str = "error"          # error | latency | hang | corrupt
                                 # | host_death | partition | straggler
    rate: float = 1.0            # per-visit injection probability
    after: int = 0               # skip the first `after` visits entirely
    count: Optional[int] = None  # at most this many injections
    error: Optional[Callable[[str], BaseException]] = None
    delay_s: float = 0.05        # latency duration / hang cap
    mutate: Optional[Callable[[Any], Any]] = None  # corrupt transform
    process_id: Optional[int] = None  # only this jax.process_index fires
    visits: int = field(default=0, compare=False)
    injected: int = field(default=0, compare=False)


def _poison_nan(value: Any) -> Any:
    """Default ``kind="corrupt"`` mutation: NaN into the first element
    of the first FLOAT leaf (copies the leaf — sources may hand out
    views of long-lived host buffers). Integer-only values pass through
    unchanged: an integer wire cannot carry NaN, which is also why the
    numerics gate streams f32."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(value)
    out = []
    poisoned = False
    for leaf in leaves:
        arr = np.asarray(leaf)
        if not poisoned and np.issubdtype(arr.dtype, np.floating) \
                and arr.size:
            arr = arr.copy()
            arr.reshape(-1)[0] = np.nan
            poisoned = True
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


_ACTIVE: Optional["FaultPlan"] = None


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules, active inside ``with``.

    Usage::

        plan = (FaultPlan(seed=7)
                .add("ingest.decode", rate=0.1)             # transient
                .add("ingest.produce", kind="hang", after=3, count=1))
        with plan:
            fit_streaming(est, stream, labels)
        assert plan.injections("ingest.decode") > 0
    """

    def __init__(self, seed: int = 0):
        # specs/log/rng are hit from every instrumented ingest thread;
        # guarded (utils.guarded.GUARDED_FIELDS declares the fields)
        self._rng = np.random.RandomState(seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._lock = TracedLock("faults")
        self._release = threading.Event()
        self.log: List[Dict[str, Any]] = []

    def add(self, site: str, kind: str = "error", rate: float = 1.0,
            after: int = 0, count: Optional[int] = None,
            error: Optional[Callable[[str], BaseException]] = None,
            delay_s: Optional[float] = None,
            mutate: Optional[Callable[[Any], Any]] = None,
            process_id: Optional[int] = None) -> "FaultPlan":
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if not 0.0 < rate <= 1.0:
            raise ValueError("rate must be in (0, 1]")
        if delay_s is None:
            # a straggler is a sustained slowdown, not an I/O blip — a
            # visibly larger default than the latency spike's 50 ms
            delay_s = 0.25 if kind == "straggler" else 0.05
        spec = FaultSpec(site=site, kind=kind, rate=rate, after=int(after),
                         count=count, error=error, delay_s=float(delay_s),
                         mutate=mutate,
                         process_id=(None if process_id is None
                                     else int(process_id)))
        self._specs.setdefault(site, []).append(spec)
        return self

    # -- activation --------------------------------------------------------
    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("another FaultPlan is already active")
        self._release.clear()
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None
        # wake every hung site so daemon threads blocked in a "hang"
        # injection finish instead of lingering for delay_s
        self._release.set()

    # -- introspection -----------------------------------------------------
    def injections(self, site: Optional[str] = None) -> int:
        with self._lock:
            return len([e for e in self.log
                        if site is None or e["site"] == site])

    # -- the injection point ----------------------------------------------
    def fire(self, site: str, context: Any,
             abort: Optional[Callable[[], bool]] = None) -> None:
        specs = self._specs.get(site)
        if not specs:
            return
        for spec in specs:
            if spec.kind == "corrupt":
                continue  # value-carrying rule: fires via corrupt()
            if (spec.process_id is not None
                    and spec.process_id != _process_index()):
                continue  # host-gated rule, dormant on this process
            with self._lock:
                spec.visits += 1
                if spec.visits <= spec.after:
                    continue
                if spec.count is not None and spec.injected >= spec.count:
                    continue
                if spec.rate < 1.0 and float(self._rng.rand()) >= spec.rate:
                    continue
                spec.injected += 1
                self.log.append({"site": site, "kind": spec.kind,
                                 "context": context})
            record_event("fault_injected", site=site, kind=spec.kind,
                         context=str(context))
            if spec.kind in ("latency", "straggler"):
                time.sleep(spec.delay_s)
            elif spec.kind == "hang":
                deadline = time.perf_counter() + spec.delay_s
                while (not self._release.wait(0.02)
                       and not (abort is not None and abort())
                       and time.perf_counter() < deadline):
                    pass
            elif spec.kind == "host_death":
                # simulate SIGKILL of this host: no flushing, no exit
                # handlers, no goodbye to the coordination service —
                # the surviving world observes a dead peer exactly as
                # it would for a real machine loss
                import os as _os
                import sys as _sys

                print(f"FAULT host_death at {site} "
                      f"(process {_process_index()}, {context})",
                      file=_sys.stderr, flush=True)
                _os._exit(HOST_DEATH_EXIT_CODE)
            elif spec.kind == "partition":
                raise PartitionError(
                    f"injected network partition at {site} "
                    f"(process {_process_index()}, {context})")
            else:
                exc = (spec.error(f"injected fault at {site} ({context})")
                       if spec.error is not None else
                       InjectedFaultError(
                           f"injected fault at {site} ({context})"))
                raise exc


    def mutate_value(self, site: str, value: Any, context: Any) -> Any:
        """Apply this plan's ``kind="corrupt"`` rules at a
        value-carrying site (same visit/after/count/rate gating as
        :meth:`fire`, same seeded RNG)."""
        specs = self._specs.get(site)
        if not specs:
            return value
        for spec in specs:
            if spec.kind != "corrupt":
                continue
            if (spec.process_id is not None
                    and spec.process_id != _process_index()):
                continue  # host-gated rule, dormant on this process
            with self._lock:
                spec.visits += 1
                if spec.visits <= spec.after:
                    continue
                if spec.count is not None and spec.injected >= spec.count:
                    continue
                if spec.rate < 1.0 and float(self._rng.rand()) >= spec.rate:
                    continue
                spec.injected += 1
                self.log.append({"site": site, "kind": "corrupt",
                                 "context": context})
            record_event("fault_injected", site=site, kind="corrupt",
                         context=str(context))
            value = (spec.mutate or _poison_nan)(value)
        return value


def inject(site: str, context: Any = None,
           abort: Optional[Callable[[], bool]] = None) -> None:
    """The per-site hook: a no-op (one global read) unless a
    :class:`FaultPlan` is active. ``abort`` lets long "hang" injections
    end early when the caller is shutting down (the stream producer
    passes its stop event)."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(site, context, abort)


def corrupt(site: str, value: Any, context: Any = None) -> Any:
    """The value-carrying injection hook (``kind="corrupt"`` rules):
    returns ``value`` untouched — one global read — unless an active
    plan has a corrupt rule at ``site``. Wired at the streaming
    ``ingest.stage`` site (the host chunk, BEFORE any wire narrowing,
    so a poisoned NaN actually survives to the device)."""
    plan = _ACTIVE
    if plan is None:
        return value
    return plan.mutate_value(site, value, context)
