"""Request micro-batching behind a slot-gated bounded queue.

Online serving gets its throughput from batching: one device dispatch
over 32 coalesced requests costs barely more than one over a single
item. The batcher is the waiting room where that coalescing happens:

* :class:`BucketPolicy` — the fixed ladder of padded batch sizes.
  Every executed batch is padded to a BUCKET (shard-rounded powers of
  two up to ``max_batch``), so variable request sizes hit one compiled
  executable per bucket (``parallel.dataset.bucketed_dataset`` + the
  existing mask machinery) and the PR 9 warmup fence can assert zero
  steady-state recompiles per request shape.
* :class:`MicroBatcher` — the bounded queue. Admission is SLOT-GATED
  before enqueue with a :class:`~keystone_tpu.utils.guarded.
  TracedSemaphore` (the ``parallel/streaming.py`` staging discipline:
  backpressure is an explicit counted gate, not implicit queue
  blocking), so pending work is provably bounded at ``queue_depth``
  requests and an overloaded plane rejects fast (429-shaped
  :class:`QueueFullError`) instead of queueing unboundedly. The worker
  side (:meth:`take`) pops the oldest request and greedily coalesces
  same-model requests behind it up to the bucket ceiling, preserving
  FIFO order for everything it leaves behind.

Thread model: HTTP handler threads (or test threads) call ``submit``;
ONE plane worker calls ``take``/``done``. ``_pending``/``_closed`` are
``@guarded_by`` the batcher lock; the ready-event wait runs OUTSIDE it
(the blocking-under-lock pass checks this).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Deque, List, Optional, Tuple

from ..observability.metrics import MetricsRegistry
from ..observability.reqtrace import ReqTrace, tracing_active
from ..parallel.dataset import padded_rows
from ..resilience.faults import inject
from ..utils.guarded import (TracedLock, TracedSemaphore, guarded_by,
                             hotpath, published_by)


class QueueFullError(RuntimeError):
    """The bounded request queue is full (slot gate refused within the
    submit timeout) — the caller should shed load / retry later.
    ``retry_after_s`` is the batcher's drain-rate-based estimate of
    when a slot will plausibly free (the HTTP surface serves it as a
    ``Retry-After`` header, so a 429 under sustained overload tells
    clients WHEN, not just no)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExpiredError(RuntimeError):
    """The request's deadline passed before the worker dispatched it —
    the request was SHED from the queue without burning device time
    (the caller's answer would have been too late anyway). The HTTP
    surface maps this to 504."""


@dataclass(frozen=True)
class BucketPolicy:
    """The pad-to-bucket ladder: shard-rounded powers of two from one
    shard's worth of rows up to ``max_batch`` (always included, so the
    ceiling is exact). Fewer buckets = fewer executables resident but
    more pad waste; powers of two cap the waste at <2x while keeping
    the executable count logarithmic in ``max_batch``."""

    max_batch: int = 64

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")

    def rows(self, shards: int) -> Tuple[int, ...]:
        """Ascending bucket row counts for a ``shards``-way data mesh
        (every entry a shard multiple, via the one padding-arithmetic
        home ``parallel.dataset.padded_rows``)."""
        sizes = set()
        b = 1
        while b < self.max_batch:
            sizes.add(padded_rows(b, shards))
            b *= 2
        sizes.add(padded_rows(self.max_batch, shards))
        return tuple(sorted(sizes))

    def bucket_for(self, n: int, shards: int) -> int:
        """Smallest bucket holding ``n`` rows (ValueError above the
        ceiling — the worker never builds a batch beyond ``max_rows``)."""
        for b in self.rows(shards):
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"({self.rows(shards)[-1]}) — split it before staging")

    def max_rows(self, shards: int) -> int:
        return self.rows(shards)[-1]


@dataclass
class Request:
    """One submitted request: ``x`` is a host pytree whose leaves have
    leading dim ``n``; the future resolves to the model output for
    exactly those ``n`` rows (pad stripped). ``trace`` is the
    request-path span record (PR 16) carried across the worker-thread
    hop — None when tracing is suppressed/disabled, and the serving
    path treats it as optional everywhere."""

    model: str
    x: Any
    n: int
    enqueued_s: float = field(default_factory=time.perf_counter)
    future: Future = field(default_factory=Future)
    trace: Optional[ReqTrace] = None
    #: absolute perf_counter deadline (None = no deadline). A request
    #: past its deadline is shed BEFORE dispatch — see
    #: ``ServingPlane._shed_expired``.
    deadline_s: Optional[float] = None

    def expired(self, now: Optional[float] = None) -> bool:
        """True when this request's deadline has passed."""
        if self.deadline_s is None:
            return False
        return (time.perf_counter() if now is None else now) \
            > self.deadline_s


@published_by("_lock", "_closed")
@guarded_by("_lock", "_pending")
class MicroBatcher:
    """Slot-gated bounded request queue; see module docstring.

    ``_closed`` is *published* rather than guarded: :meth:`submit_request`
    reads it lock-free before paying the slot gate, so a closed batcher
    refuses instantly instead of blocking the submit timeout and
    mis-reporting shutdown as a 429. Writes stay atomic rebinds under
    the lock (the publication pass checks this)."""

    def __init__(self, queue_depth: int = 128,
                 submit_timeout_s: float = 2.0):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.queue_depth = int(queue_depth)
        self.submit_timeout_s = float(submit_timeout_s)
        self._slots = TracedSemaphore("serving.queue_slots", queue_depth)
        self._lock = TracedLock("serving.batcher")
        self._pending: Deque[Request] = deque()
        self._closed = False
        self._ready = threading.Event()
        # drain-rate EWMA (requests/s, fed by done()): the basis of the
        # Retry-After hint a 429 carries. 0.0 = never drained yet.
        self._drain_rps = 0.0
        self._last_done_s = time.perf_counter()

    def retry_after_s(self) -> float:
        """Seconds until a queue slot plausibly frees: pending depth
        over the observed drain rate, clamped to [0.05, 10]. Before any
        drain is observed the submit timeout is the honest hint."""
        rate = self._drain_rps
        if rate <= 0.0:
            return max(self.submit_timeout_s, 0.05)
        with self._lock:
            depth = len(self._pending)
        return min(max(max(depth, 1) / rate, 0.05), 10.0)

    # -- producer side (handler threads) -----------------------------------
    @hotpath
    def submit(self, model: str, x: Any, n: int,
               timeout_s: Optional[float] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request behind the slot gate; returns its
        future. Raises :class:`QueueFullError` when no slot frees
        within the timeout (bounded queue = bounded latency: better an
        honest 429 than an unbounded wait)."""
        return self.submit_request(model, x, n, timeout_s=timeout_s,
                                   deadline_ms=deadline_ms).future

    @hotpath
    def submit_request(self, model: str, x: Any, n: int,
                       timeout_s: Optional[float] = None,
                       deadline_ms: Optional[float] = None) -> Request:
        """:meth:`submit`, returning the whole :class:`Request` — the
        trace-aware spelling (the HTTP surface echoes
        ``request.trace.trace_id`` back as ``X-Keystone-Trace``).
        ``deadline_ms`` is a client budget relative to enqueue: a
        request still queued past it is shed before dispatch."""
        inject("serve.enqueue", context=model)
        # lock-free published read: a closed batcher refuses BEFORE the
        # slot gate, so shutdown never costs callers the submit timeout
        # nor masquerades as a QueueFullError 429
        if self._closed:
            raise RuntimeError("batcher is closed")
        timeout = self.submit_timeout_s if timeout_s is None else timeout_s
        if not self._slots.acquire(timeout=timeout):
            reg = MetricsRegistry.get_or_create()
            reg.counter("serving.rejected_total").inc()
            # the per-model family: a 429 storm names its model
            reg.counter(f"serving.rejected_total.{model}").inc()
            raise QueueFullError(
                f"serving queue full ({self.queue_depth} slots) — "
                f"request for {model!r} rejected after {timeout:.1f}s",
                retry_after_s=self.retry_after_s())
        trace = ReqTrace.new(model, int(n)) if tracing_active() else None
        if trace is None:
            req = Request(model=model, x=x, n=int(n))
        else:
            # one clock read stamps both records: the trace's
            # enqueued_s IS the request's (the telescoping invariant
            # starts here)
            req = Request(model=model, x=x, n=int(n),
                          enqueued_s=trace.enqueued_s, trace=trace)
        if deadline_ms is not None:
            req.deadline_s = req.enqueued_s + float(deadline_ms) / 1e3
        with self._lock:
            if self._closed:
                self._slots.release()
                raise RuntimeError("batcher is closed")
            self._pending.append(req)
            depth = len(self._pending)
        self._ready.set()
        MetricsRegistry.get_or_create().gauge(
            "serving.queue_depth").set(depth)
        return req

    # -- consumer side (the plane worker) ----------------------------------
    @hotpath
    def take(self, max_rows: int, timeout_s: float = 0.05) -> List[Request]:
        """Pop the oldest pending request plus every later SAME-model
        request that fits within ``max_rows`` total rows; requests for
        other models (and overflow) keep their FIFO positions. Returns
        [] on timeout. The event wait runs outside the lock."""
        if not self._ready.wait(timeout_s):
            return []
        out: List[Request] = []
        with self._lock:
            if not self._pending:
                self._ready.clear()
                return []
            first = self._pending.popleft()
            out.append(first)
            rows = first.n
            rest: Deque[Request] = deque()
            while self._pending:
                req = self._pending.popleft()
                if req.model == first.model and rows + req.n <= max_rows:
                    out.append(req)
                    rows += req.n
                else:
                    rest.append(req)
            self._pending = rest
            if not self._pending:
                self._ready.clear()
            depth = len(self._pending)
        taken_s = time.perf_counter()
        for req in out:
            if req.trace is not None:
                # queue_wait ends here; the worker owns later stamps
                req.trace.taken_s = taken_s
        MetricsRegistry.get_or_create().gauge(
            "serving.queue_depth").set(depth)
        return out

    @hotpath
    def done(self, count: int) -> None:
        """Free ``count`` slots once their requests' futures resolved —
        the release half of the staging discipline: live queue
        occupancy provably never exceeds ``queue_depth``. Also feeds
        the drain-rate EWMA behind :meth:`retry_after_s` (two float
        writes — single-writer: only the plane worker calls done)."""
        if count > 0:
            now = time.perf_counter()
            dt = max(now - self._last_done_s, 1e-6)
            self._last_done_s = now
            sample = count / dt
            prior = self._drain_rps
            self._drain_rps = sample if prior <= 0.0 \
                else 0.8 * prior + 0.2 * sample
            self._slots.release(count)

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self) -> List[Request]:
        """Refuse new submits and drain the queue; returns the drained
        requests so the owner can fail their futures loudly."""
        with self._lock:
            self._closed = True
            drained = list(self._pending)
            self._pending = deque()
            self._ready.clear()
        if drained:
            self._slots.release(len(drained))
        return drained
