"""Low-latency multi-tenant serving plane (ROADMAP item 1).

Fitted pipelines admitted as warm device-resident compiled executables,
request micro-batching behind a slot-gated bounded queue (pad-to-bucket,
one executable per bucket, zero steady-state recompiles asserted by the
compile-observatory fence), and multi-model residency under an explicit
HBM budget with static-planner admission charges and LRU-with-cost
eviction. ``python -m keystone_tpu serve`` is the CLI;
``ServingPlane`` the embeddable core. See README "Serving".
"""
from .batcher import (
    BucketPolicy,
    DeadlineExpiredError,
    MicroBatcher,
    QueueFullError,
    Request,
)
from .plane import (
    ModelNotAdmitted,
    ModelWarming,
    PoisonedBatchError,
    ServedModel,
    ServingPlane,
)
from .residency import AdmissionError, ModelCharge, ResidencyLedger, model_charge

__all__ = [
    "AdmissionError",
    "BucketPolicy",
    "DeadlineExpiredError",
    "MicroBatcher",
    "ModelCharge",
    "ModelNotAdmitted",
    "ModelWarming",
    "PoisonedBatchError",
    "QueueFullError",
    "Request",
    "ResidencyLedger",
    "ServedModel",
    "ServingPlane",
    "model_charge",
]
