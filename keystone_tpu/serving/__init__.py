"""Low-latency multi-tenant serving plane (ROADMAP item 1).

Fitted pipelines admitted as warm device-resident compiled executables,
request micro-batching behind a slot-gated bounded queue (pad-to-bucket,
one executable per bucket, zero steady-state recompiles asserted by the
compile-observatory fence), and multi-model residency under an explicit
HBM budget with static-planner admission charges and LRU-with-cost
eviction. ``python -m keystone_tpu serve`` is the CLI;
``ServingPlane`` the embeddable core. See README "Serving".

The fleet layer (ISSUE 20) scales the plane out: ``plan_placement``
packs models onto replicas under per-replica budgets, ``FleetRouter``
fronts N replicas with rendezvous routing and honest spill,
``FleetController`` owns the canonical model bytes and applies every
placement change admit -> sha-verify -> evict, and ``FleetAutoscaler``
turns scraped telemetry into membership changes. See README "Fleet
serving" and CLUSTER.md "Fleet topology".
"""
from .batcher import (
    BucketPolicy,
    DeadlineExpiredError,
    MicroBatcher,
    QueueFullError,
    Request,
)
from .fleet import (
    FleetAutoscaler,
    FleetController,
    FleetError,
    FleetModel,
    run_reactor,
)
from .placement import ModelDemand, Placement, PlacementError, plan_placement
from .plane import (
    ModelNotAdmitted,
    ModelWarming,
    PoisonedBatchError,
    ServedModel,
    ServingPlane,
)
from .residency import AdmissionError, ModelCharge, ResidencyLedger, model_charge
from .router import (
    FleetRouter,
    HttpReplicaClient,
    LocalReplicaClient,
    serve_router,
)

__all__ = [
    "AdmissionError",
    "BucketPolicy",
    "DeadlineExpiredError",
    "FleetAutoscaler",
    "FleetController",
    "FleetError",
    "FleetModel",
    "FleetRouter",
    "HttpReplicaClient",
    "LocalReplicaClient",
    "MicroBatcher",
    "ModelCharge",
    "ModelDemand",
    "ModelNotAdmitted",
    "ModelWarming",
    "Placement",
    "PlacementError",
    "PoisonedBatchError",
    "QueueFullError",
    "Request",
    "ResidencyLedger",
    "ServedModel",
    "ServingPlane",
    "model_charge",
    "plan_placement",
    "run_reactor",
    "serve_router",
]
