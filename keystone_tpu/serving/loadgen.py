"""Deterministic trace-replay load generation for the serving plane.

The serving bench used to drive 4 uniform client threads — which is
not traffic. Production request streams are bursty (correlated
arrivals), diurnal (rate swings over the window), skewed (a few hot
models take most requests — Zipf), mixed-size, and churny (models
admitted/evicted/readmitted under live load). This module generates
such a stream DETERMINISTICALLY, the ``resilience/faults.py`` way: a
:class:`LoadSpec` plus a seed is the whole experiment, and the same
seed always yields the identical arrival/model/size sequence (pinned
by test), so a chaos-scenario failure replays exactly.

Two halves:

* :func:`generate_trace` — pure function ``spec -> LoadTrace``: the
  timestamped request events (arrival offset, model, row count) and
  churn events (evict/readmit at an offset). No wall clock, no global
  state; all randomness comes from one ``np.random.RandomState(seed)``.
* :func:`replay` — drives a generated trace against a live
  :class:`~.plane.ServingPlane` with a small deterministic-assignment
  sender pool (event ``i`` goes to sender ``i mod senders``, so the
  submission ORDER per sender is reproducible even though wall-clock
  interleaving is not), applies churn events from a separate driver
  thread, and classifies every outcome — ``ok``/``rejected`` (429)/
  ``shed`` (deadline)/``poisoned``/``not_admitted``/``warming``/
  ``error``/``unclassified`` — into a :class:`ReplayReport`. The
  ``unclassified`` bucket existing (and being asserted zero by every
  chaos scenario) is the point: under injected faults, every request
  must end in a KNOWN verdict.

Availability in the report is ACCEPTED-request availability: of the
requests that made it past the slot gate into the queue, the fraction
that resolved OK. Rejections (backpressure working) and routing
verdicts during churn (not-admitted / warming) are honest
classifications counted separately — each scenario asserts its own
bounds on them.
"""
from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: arrival process names generate_trace understands
ARRIVALS = ("poisson", "bursty", "diurnal")

#: every outcome class replay can record — scenarios assert
#: ``unclassified == 0`` (a fault run must end clean or CLASSIFIED)
OUTCOMES = ("ok", "rejected", "shed", "poisoned", "not_admitted",
            "warming", "error", "unclassified")


@dataclass(frozen=True)
class RequestEvent:
    """One generated request: fires ``t_s`` seconds into the replay."""

    t_s: float
    model: str
    n: int
    seq: int


@dataclass(frozen=True)
class ChurnEvent:
    """One residency change under live load: ``action`` is ``"evict"``
    or ``"readmit"`` (readmission IS admission under load — it runs the
    full warmup path)."""

    t_s: float
    action: str
    model: str


@dataclass(frozen=True)
class LoadSpec:
    """One traffic experiment, fully determined by its fields + seed.

    ``rate_rps`` is the MEAN arrival rate; ``arrival`` shapes how it is
    spent: ``poisson`` (memoryless), ``bursty`` (on/off modulated:
    dwell times are exponential with means ``burst_on_s``/
    ``burst_off_s``; the on-state rate is scaled so the MEAN stays
    ``rate_rps``), or ``diurnal`` (sinusoidal rate over
    ``diurnal_period_s``, thinned from the peak rate). Model popularity
    is Zipf over ``models`` rank order (``zipf_s`` the exponent); sizes
    draw from ``sizes`` with probability inversely proportional to the
    size (most requests are small, like real traffic)."""

    seed: int = 0
    duration_s: float = 2.0
    rate_rps: float = 200.0
    arrival: str = "poisson"
    models: Tuple[str, ...] = ("m0",)
    zipf_s: float = 1.1
    sizes: Tuple[int, ...] = (1, 2, 4)
    burst_mult: float = 4.0
    burst_on_s: float = 0.25
    burst_off_s: float = 0.25
    diurnal_amp: float = 0.8
    diurnal_period_s: float = 1.0
    churn: Tuple[ChurnEvent, ...] = ()
    deadline_ms: Optional[float] = None

    def __post_init__(self):
        if self.arrival not in ARRIVALS:
            raise ValueError(f"unknown arrival process {self.arrival!r} "
                             f"(know {ARRIVALS})")
        if self.duration_s <= 0 or self.rate_rps <= 0:
            raise ValueError("duration_s and rate_rps must be > 0")
        if not self.models or not self.sizes:
            raise ValueError("models and sizes must be non-empty")
        if not 0.0 <= self.diurnal_amp < 1.0:
            raise ValueError("diurnal_amp must be in [0, 1)")


@dataclass(frozen=True)
class LoadTrace:
    """A generated experiment: request events sorted by arrival offset
    plus the spec's churn events (also time-sorted)."""

    spec: LoadSpec
    arrivals: Tuple[RequestEvent, ...]
    churn: Tuple[ChurnEvent, ...]


def _zipf_pmf(k: int, s: float) -> np.ndarray:
    w = np.arange(1, k + 1, dtype=np.float64) ** (-float(s))
    return w / w.sum()


def _size_pmf(sizes: Tuple[int, ...]) -> np.ndarray:
    w = 1.0 / np.asarray(sizes, dtype=np.float64)
    return w / w.sum()


def _poisson_times(rng: np.random.RandomState, rate: float,
                   t0: float, t1: float) -> List[float]:
    out: List[float] = []
    t = t0
    while True:
        t += float(rng.exponential(1.0 / rate))
        if t >= t1:
            return out
        out.append(t)


def _arrival_times(spec: LoadSpec,
                   rng: np.random.RandomState) -> List[float]:
    if spec.arrival == "poisson":
        return _poisson_times(rng, spec.rate_rps, 0.0, spec.duration_s)
    if spec.arrival == "bursty":
        # alternating on/off dwells; the on-rate is solved so the
        # long-run mean is rate_rps: mean = on_rate * on_frac
        on_frac = spec.burst_on_s / (spec.burst_on_s + spec.burst_off_s)
        on_rate = spec.rate_rps * min(spec.burst_mult, 1.0 / on_frac)
        times: List[float] = []
        t, on = 0.0, True
        while t < spec.duration_s:
            dwell = float(rng.exponential(
                spec.burst_on_s if on else spec.burst_off_s))
            end = min(t + dwell, spec.duration_s)
            if on:
                times.extend(_poisson_times(rng, on_rate, t, end))
            t, on = end, not on
        return times
    # diurnal: thin a peak-rate stream down to the sinusoidal profile
    peak = spec.rate_rps * (1.0 + spec.diurnal_amp)
    times = []
    for t in _poisson_times(rng, peak, 0.0, spec.duration_s):
        rate_t = spec.rate_rps * (1.0 + spec.diurnal_amp * math.sin(
            2.0 * math.pi * t / spec.diurnal_period_s))
        if float(rng.rand()) < rate_t / peak:
            times.append(t)
    return times


def generate_trace(spec: LoadSpec) -> LoadTrace:
    """``spec -> LoadTrace``, deterministically: one seeded RNG decides
    arrivals, then per-event model and size — so two calls with the
    same spec produce IDENTICAL event sequences (the pinned contract),
    and a scenario failure names (spec, seed) as its full repro."""
    rng = np.random.RandomState(spec.seed)
    times = _arrival_times(spec, rng)
    model_p = _zipf_pmf(len(spec.models), spec.zipf_s)
    size_p = _size_pmf(spec.sizes)
    model_idx = rng.choice(len(spec.models), size=len(times), p=model_p)
    size_idx = rng.choice(len(spec.sizes), size=len(times), p=size_p)
    arrivals = tuple(
        RequestEvent(t_s=float(t), model=spec.models[int(m)],
                     n=int(spec.sizes[int(s)]), seq=i)
        for i, (t, m, s) in enumerate(zip(times, model_idx, size_idx)))
    return LoadTrace(spec=spec, arrivals=arrivals,
                     churn=tuple(sorted(spec.churn,
                                        key=lambda c: c.t_s)))


@dataclass
class ReplayReport:
    """What happened when a trace was replayed: outcome counts, OK
    latencies, churn results, and a bounded sample of error texts."""

    outcomes: Dict[str, int] = field(
        default_factory=lambda: {k: 0 for k in OUTCOMES})
    latencies_ms: List[float] = field(default_factory=list)
    retry_after_seen: int = 0     # rejections that carried a hint
    postmortems: List[str] = field(default_factory=list)
    churn_applied: int = 0
    churn_failed: int = 0
    errors: List[str] = field(default_factory=list)  # bounded sample
    wall_s: float = 0.0

    @property
    def total(self) -> int:
        return sum(self.outcomes.values())

    @property
    def accepted(self) -> int:
        """Requests that entered the queue (past the slot gate)."""
        return self.total - self.outcomes["rejected"] \
            - self.outcomes["not_admitted"] - self.outcomes["warming"]

    def availability(self) -> float:
        """OK fraction of ACCEPTED requests (see module docstring)."""
        acc = self.accepted
        return self.outcomes["ok"] / acc if acc else 1.0

    def p99_ms(self) -> float:
        if not self.latencies_ms:
            return 0.0
        return float(np.percentile(np.asarray(self.latencies_ms), 99))

    def p50_ms(self) -> float:
        if not self.latencies_ms:
            return 0.0
        return float(np.percentile(np.asarray(self.latencies_ms), 50))

    def summary(self) -> Dict[str, Any]:
        return {
            "outcomes": dict(self.outcomes),
            "p50_ms": round(self.p50_ms(), 3),
            "p99_ms": round(self.p99_ms(), 3),
            "availability": round(self.availability(), 4),
            "accepted": self.accepted,
            "retry_after_seen": self.retry_after_seen,
            "churn_applied": self.churn_applied,
            "churn_failed": self.churn_failed,
            "wall_s": round(self.wall_s, 3),
        }


def _classify(exc: BaseException) -> str:
    # local imports keep loadgen importable without pulling jax at
    # module-import time (the trace half is pure host python)
    from concurrent.futures import TimeoutError as _FutTimeout

    from ..resilience.retry import TransientError
    from .batcher import DeadlineExpiredError, QueueFullError
    from .plane import ModelNotAdmitted, ModelWarming, PoisonedBatchError

    if isinstance(exc, QueueFullError):
        return "rejected"
    if isinstance(exc, DeadlineExpiredError):
        return "shed"
    if isinstance(exc, PoisonedBatchError):
        return "poisoned"
    if isinstance(exc, ModelNotAdmitted):
        return "not_admitted"
    if isinstance(exc, ModelWarming):
        return "warming"
    if isinstance(exc, (TransientError, ConnectionError, RuntimeError,
                        TimeoutError, _FutTimeout)):
        return "error"
    return "unclassified"


class HttpServingClient:
    """A plane-shaped adapter over a real HTTP serving endpoint (one
    replica or the fleet router — same wire surface), so
    :func:`replay` drives real sockets with zero changes: the sender
    pool calls ``submit_request`` exactly as it would on a plane, the
    POST happens synchronously inside it, and the HTTP status comes
    back RECONSTRUCTED as the serving exception it encodes (429 ->
    ``QueueFullError`` carrying the ``Retry-After`` hint, 503 ->
    ``ModelWarming`` or router-unavailable, 404 -> ``ModelNotAdmitted``,
    504 -> ``DeadlineExpiredError``, 500 -> ``PoisonedBatchError`` when
    the body names it). The classifier then lands every outcome in the
    same bucket it would land for an in-process plane — the chaos
    floors and the fleet gate assert over ONE vocabulary regardless of
    transport. Connection failures surface as ``ConnectionError``
    (classified ``error``): a dead replica mid-kill is an honest,
    counted outcome, never an unclassified crash."""

    def __init__(self, host: str, port: int,
                 request_timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.request_timeout_s = float(request_timeout_s)

    def _raise_for(self, status: int, body: bytes,
                   headers: Dict[str, str]) -> None:
        from .batcher import DeadlineExpiredError, QueueFullError
        from .plane import (ModelNotAdmitted, ModelWarming,
                            PoisonedBatchError)

        try:
            text = json.loads(body or b"{}").get("error", "")
        except ValueError:
            text = body[:200].decode(errors="replace")
        retry_after = float(headers.get("Retry-After", 1.0) or 1.0)
        if status == 429:
            raise QueueFullError(text or "queue full",
                                 retry_after_s=retry_after)
        if status == 503:
            if "ModelWarming" in text:
                raise ModelWarming(text)
            raise QueueFullError(text or "unavailable",
                                 retry_after_s=retry_after)
        if status == 404:
            raise ModelNotAdmitted(text or "not admitted")
        if status == 504:
            raise DeadlineExpiredError(text or "deadline expired")
        if status == 500 and "PoisonedBatchError" in text:
            raise PoisonedBatchError(text)
        # 400 and the rest are honest errors, never unclassified —
        # RuntimeError (not ValueError) keeps the classifier verdict
        raise RuntimeError(f"HTTP {status}: {text or body[:200]!r}")

    def submit_request(self, model: str, x: Any,
                       timeout_s: Optional[float] = None,
                       deadline_ms: Optional[float] = None) -> Any:
        import http.client
        from concurrent.futures import Future

        from .batcher import Request

        payload: Dict[str, Any] = {
            "instances": np.asarray(x).tolist()}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.request_timeout_s)
        try:
            try:
                conn.request("POST", f"/predict/{model}",
                             body=json.dumps(payload).encode(),
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                body = resp.read()
                headers = {k: v for k, v in resp.getheaders()}
                status = resp.status
            except (OSError, http.client.HTTPException) as exc:
                raise ConnectionError(
                    f"{self.host}:{self.port}: {exc}") from exc
        finally:
            conn.close()
        if status != 200:
            self._raise_for(status, body, headers)
        out = json.loads(body)
        future: Future = Future()
        future.set_result(np.asarray(out["predictions"]))
        return Request(model=model, x=x, n=int(out.get("rows", 1)),
                       enqueued_s=time.perf_counter(), future=future)


def replay(trace: LoadTrace, plane: Any,
           input_for: Callable[[str, int], Any],
           senders: int = 4, time_scale: float = 1.0,
           submit_timeout_s: float = 0.25,
           result_timeout_s: float = 30.0) -> ReplayReport:
    """Replay ``trace`` against ``plane``; see module docstring.

    ``input_for(model, n)`` builds the request payload (the scenario
    owns model shapes). ``time_scale`` stretches (>1) or compresses
    (<1) the arrival clock — the event SEQUENCE is untouched."""
    report = ReplayReport()
    stats_lock = threading.Lock()
    err_cap = 16
    t_start = time.perf_counter()

    def record(outcome: str, latency_ms: Optional[float] = None,
               exc: Optional[BaseException] = None) -> None:
        with stats_lock:
            report.outcomes[outcome] += 1
            if latency_ms is not None:
                report.latencies_ms.append(latency_ms)
            if exc is not None:
                retry_after = getattr(exc, "retry_after_s", None)
                if outcome == "rejected" and retry_after is not None:
                    report.retry_after_seen += 1
                pm = getattr(exc, "postmortem_path", None)
                if pm:
                    report.postmortems.append(pm)
                if len(report.errors) < err_cap:
                    report.errors.append(
                        f"{type(exc).__name__}: {exc}")

    def sender(idx: int) -> None:
        for ev in trace.arrivals[idx::senders]:
            due = t_start + ev.t_s * time_scale
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            try:
                req = plane.submit_request(
                    ev.model, input_for(ev.model, ev.n),
                    timeout_s=submit_timeout_s,
                    deadline_ms=trace.spec.deadline_ms)
                req.future.result(timeout=result_timeout_s)
                record("ok", (time.perf_counter() - t0) * 1e3)
            except BaseException as exc:
                record(_classify(exc), exc=exc)

    def churner() -> None:
        for ev in trace.churn:
            due = t_start + ev.t_s * time_scale
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                if ev.action == "evict":
                    plane.evict(ev.model)
                elif ev.action == "readmit":
                    plane.readmit(ev.model)
                else:
                    raise ValueError(
                        f"unknown churn action {ev.action!r}")
                with stats_lock:
                    report.churn_applied += 1
            except BaseException as exc:
                with stats_lock:
                    report.churn_failed += 1
                    if len(report.errors) < err_cap:
                        report.errors.append(
                            f"churn {ev.action} {ev.model}: "
                            f"{type(exc).__name__}: {exc}")

    threads = [threading.Thread(target=sender, args=(i,),
                                name=f"loadgen-sender-{i}", daemon=True)
               for i in range(max(int(senders), 1))]
    if trace.churn:
        threads.append(threading.Thread(target=churner,
                                        name="loadgen-churn",
                                        daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    report.wall_s = time.perf_counter() - t_start
    return report
