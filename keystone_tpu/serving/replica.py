"""One fleet replica: a serving plane that starts EMPTY and takes its
models over HTTP from the fleet controller.

``python -m keystone_tpu.serving.replica --port 0`` binds a plane with
zero models and prints the machine-parseable ``replica on HOST:PORT``
line (the fleet gate and chaos scenarios parse it, the same contract
as the single-process server's ``serving on ...``). Everything a
replica hosts arrives through the admin surface:

* ``POST /admin/admit`` — ``{"name", "blob_b64", "sample",
  "weight_dtype"}``: the controller ships the CANONICAL pickled bytes
  (the plane's own ``entry.blob`` currency), the replica admits and
  answers ``{"sha256", "charge_nbytes", "warmup_s"}``. The sha is the
  migration bit-identity verdict: the controller compares it against
  the source replica's before it evicts anything (admit -> verify ->
  evict, never a lossy hop).
* ``POST /admin/evict`` — ``{"name"}``: the drain half of a migration.
* ``GET /admin/models`` — ``{name: sha256}`` for every live model: what
  this replica would answer for, byte-attested.

The predict surface is inherited UNCHANGED from
:class:`~.http.ServingHandler` — a replica is a plain serving process
plus an admin plane; clients cannot tell the difference, which is what
lets the router front either. Admin calls are cold-path by design
(admission compiles, eviction republishes) and never run per request.

The admin payloads carry pickled bytes, so a replica trusts its
controller exactly as far as a checkpoint file trusts its writer —
bind admin surfaces to loopback or an equally private interface.
"""
from __future__ import annotations

import base64
import hashlib
import json
import pickle
import sys
import threading
from typing import Any, Dict, List, Optional

from ..observability.metrics import MetricsRegistry
from .http import ServingHandler, _err, bind_server
from .plane import ModelNotAdmitted, ServingPlane
from .residency import AdmissionError


def encode_sample_spec(sample: Any) -> str:
    """The admitted-sample wire form (base64 pickle): samples are
    host-side numpy pytrees whose shape/dtype drive the warmup
    compiles — shipped exactly, not re-derived."""
    return base64.b64encode(pickle.dumps(sample)).decode()


def decode_sample_spec(spec: str) -> Any:
    return pickle.loads(base64.b64decode(spec))


class ReplicaAdminHandler(ServingHandler):
    """The replica's HTTP surface: the full predict/observability
    surface by inheritance, plus the controller-facing admin plane."""

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        if self.path.split("?")[0] == "/admin/models":
            shas = {name: hashlib.sha256(entry.blob).hexdigest()
                    for name, entry in sorted(self.plane._live.items())}
            self._reply(200, json.dumps(shas).encode())
            return
        super().do_GET()

    def do_POST(self):  # noqa: N802 (stdlib handler API)
        path = self.path.split("?")[0]
        if not path.startswith("/admin/"):
            super().do_POST()
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError) as exc:
            self._reply(400, _err(exc))
            return
        if path == "/admin/admit":
            self._admit(payload)
        elif path == "/admin/evict":
            self._evict(payload)
        else:
            self._reply(404, b'{"error": "unknown admin endpoint"}\n')

    def _admit(self, payload: Dict[str, Any]) -> None:
        try:
            name = payload["name"]
            blob = base64.b64decode(payload["blob_b64"])
            sample = decode_sample_spec(payload["sample"])
            weight_dtype = payload.get("weight_dtype")
        except (KeyError, TypeError, ValueError) as exc:
            self._reply(400, _err(exc))
            return
        try:
            entry = self.plane.admit(name, pickle.loads(blob), sample,
                                     weight_dtype=weight_dtype)
        except AdmissionError as exc:
            # the replica's honest refusal: over-budget admission is
            # the CONTROLLER's planning error to hear about, loudly
            self._reply(507, _err(exc))
            return
        except Exception as exc:  # noqa: BLE001 (verdict, not a crash)
            self._reply(500, _err(exc))
            return
        self._reply(200, json.dumps({
            "name": name,
            "sha256": hashlib.sha256(entry.blob).hexdigest(),
            "charge_nbytes": entry.charge.total_nbytes(),
            "warmup_s": entry.warmup_s,
        }).encode())

    def _evict(self, payload: Dict[str, Any]) -> None:
        name = payload.get("name")
        if not isinstance(name, str) or not name:
            self._reply(400, b'{"error": "evict needs a model name"}\n')
            return
        try:
            self.plane.evict(name)
        except (ModelNotAdmitted, KeyError) as exc:
            self._reply(404, _err(exc))
            return
        self._reply(200, json.dumps({"evicted": name}).encode())


def serve_replica(plane: ServingPlane, port: int = 0,
                  host: str = "127.0.0.1",
                  registry: Optional[MetricsRegistry] = None):
    """Bind a replica (predict + admin surfaces) on ``host:port``."""
    return bind_server(
        ReplicaAdminHandler,
        {"registry": registry, "plane": plane,
         "ready_probe": staticmethod(plane.ready)},
        port=port, host=host, thread_name="keystone-replica-http")


def _pop_flag(argv: List[str], flag: str,
              default: Optional[str] = None) -> Optional[str]:
    if flag not in argv:
        return default
    i = argv.index(flag)
    if i + 1 >= len(argv):
        raise ValueError(f"{flag} requires a value")
    value = argv[i + 1]
    del argv[i:i + 2]
    return value


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m keystone_tpu.serving.replica`` — see module
    docstring. Starts empty; models arrive via ``/admin/admit``."""
    from ..__main__ import _parse_bytes

    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        port = int(_pop_flag(argv, "--port", "0"))
        host = _pop_flag(argv, "--host", "127.0.0.1")
        budget_text = _pop_flag(argv, "--hbm-budget")
        budget = None if budget_text is None else _parse_bytes(budget_text)
        max_batch = int(_pop_flag(argv, "--max-batch", "64"))
        queue_depth = int(_pop_flag(argv, "--queue-depth", "256"))
        workers_text = _pop_flag(argv, "--workers")
        workers = None if workers_text is None else int(workers_text)
    except ValueError as exc:
        print(f"replica: {exc}", file=sys.stderr)
        return 2
    if argv:
        print(f"replica: unknown arguments {argv}", file=sys.stderr)
        return 2
    plane = ServingPlane(hbm_budget=budget, max_batch=max_batch,
                         queue_depth=queue_depth, workers=workers)
    plane.start()
    server = serve_replica(plane, port=port, host=host)
    print(f"replica on {host}:{server.server_port}", flush=True)
    try:
        threading.Event().wait()  # serve until killed by the fleet
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        plane.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
