"""HBM admission control for the serving plane.

Multi-model residency runs under an EXPLICIT budget: a fitted pipeline
is only admitted when its charge — persistent fitted state plus the
per-bucket activation bound, both from the static planner
(``analysis/resources.py``) — fits next to the models already warm.
The arithmetic is the one the :class:`~keystone_tpu.analysis.resources.
HbmPlan` docstring documents (``serving residency ~= model_nbytes +
batch x apply_item_nbytes``); this module turns it from a comment into
the enforced contract:

* :func:`model_charge` — derive one model's :class:`ModelCharge` from a
  device-free ``fitted.check(sample)`` static plan; when the plan
  cannot size the per-item activation (opaque host stages), fall back
  to a measured one-item probe apply, with the provenance recorded on
  the charge (``source``) so an operator can see which models are
  planned vs probed.
* :class:`ResidencyLedger` — the charged-bytes ledger
  (``@guarded_by``-declared, like the streaming ``_Residency`` ledger
  it mirrors): admission atomically applies the planned evictions and
  charges the newcomer, or raises :class:`AdmissionError` without
  mutating anything — over-budget admission is REFUSED, never absorbed.

Placement/eviction policy (which models to keep when space runs out)
lives in ``serving/plane.py`` and reuses the auto-cache
profile-under-budget greedy (``workflow/optimizer/auto_cache.py:
greedy_select``); this module only accounts and enforces.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..utils.guarded import TracedLock, guarded_by


class AdmissionError(MemoryError):
    """A model admission would exceed the serving HBM budget (even
    after every allowed eviction). The message names the charge, the
    budget, and what is currently resident."""


@dataclass(frozen=True)
class ModelCharge:
    """One served model's HBM admission charge.

    ``model_nbytes`` is the persistent fitted state
    (:func:`~keystone_tpu.analysis.resources.fitted_model_nbytes`);
    ``item_nbytes`` the widest per-item activation along the apply path
    (``HbmPlan.apply_item_nbytes``, or a probed measurement);
    ``bucket_rows`` the LARGEST request bucket the model will serve —
    the activation bound is charged at the worst case, so a full bucket
    arriving never busts the budget at runtime. ``source`` records the
    provenance (``static-plan`` | ``probed``).

    ``data_shards > 1`` makes :meth:`total_nbytes` the PER-HOST charge
    under the sharded apply (``parallel/spmd_apply.py``): the
    ``shardable_nbytes`` portion of the model divides across the data
    axis, one ``gather_nbytes`` transient is charged for the in-body
    all_gather, and the activation is this host's row shard of the
    bucket — so admission can place a model whose total
    ``model_nbytes`` exceeds one host's budget."""

    model_nbytes: float
    item_nbytes: float
    bucket_rows: int
    source: str = "static-plan"
    data_shards: int = 1
    shardable_nbytes: float = 0.0
    gather_nbytes: float = 0.0

    def activation_nbytes(self) -> float:
        shards = max(int(self.data_shards), 1)
        shard_rows = -(-int(self.bucket_rows) // shards)
        return float(self.item_nbytes) * float(shard_rows)

    def total_nbytes(self) -> float:
        shards = max(int(self.data_shards), 1)
        shardable = min(float(self.shardable_nbytes),
                        float(self.model_nbytes))
        resident = float(self.model_nbytes) - shardable + shardable / shards
        gather = float(self.gather_nbytes) if shards > 1 else 0.0
        return resident + gather + self.activation_nbytes()


def _probe_item_nbytes(fitted, sample_struct) -> float:
    """Measured fallback for plan-unresolved pipelines: apply ONE
    zero item and read the device bytes of input + output — honest
    device evidence instead of an invented number (the plan's
    ``unresolved`` contract), at the cost of one tiny apply before the
    admission decision."""
    import jax
    import numpy as np

    from ..parallel.dataset import ArrayDataset, device_nbytes

    def zero(leaf):
        return np.zeros((1,) + tuple(leaf.shape), np.dtype(leaf.dtype))

    data = jax.tree_util.tree_map(
        zero, sample_struct,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
    ds = ArrayDataset.from_numpy(data)
    out = fitted.apply(ds).get()
    rows = max(getattr(out, "padded_n", len(out)), 1)
    return (device_nbytes(ds) / max(ds.padded_n, 1)
            + device_nbytes(out) / rows)


def model_charge(fitted, sample_struct, bucket_rows: int,
                 name: str = "model", data_shards: int = 1) -> ModelCharge:
    """Derive the admission charge for ``fitted`` serving items of
    ``sample_struct`` (a ``jax.ShapeDtypeStruct`` pytree describing ONE
    request item) at a largest bucket of ``bucket_rows`` rows.

    Device-free when the static plan resolves: the pipeline is
    ``check``-ed on the item spec with unknown ``n`` (the apply-path
    view), ``apply_item_nbytes`` sizes the activation and
    ``fitted_model_nbytes`` the resident parameters. A plan that cannot
    size the activation falls back to the one-item probe.

    ``data_shards > 1`` sizes the PER-HOST charge under the sharded
    apply: the mappers' ``sharded_apply_nbytes`` hooks say how much of
    the fitted state row-shards at rest and how large the gather
    transient is (see :class:`ModelCharge`)."""
    from ..analysis.resources import (
        fitted_model_nbytes,
        serving_residency_nbytes,
        sharded_apply_nbytes,
    )

    graph = fitted.to_pipeline().graph
    report = fitted.check(sample_struct, name=f"serve:{name}")
    model_b = fitted_model_nbytes(graph)
    shards = max(int(data_shards), 1)
    shardable = gather = 0.0
    if shards > 1:
        shardable, gather = sharded_apply_nbytes(graph)
    total = serving_residency_nbytes(
        model_b, report.plan, bucket_rows, data_shards=shards,
        shardable_nbytes=shardable, gather_nbytes=gather)
    if total is not None:
        return ModelCharge(model_nbytes=model_b,
                           item_nbytes=float(report.plan.apply_item_nbytes),
                           bucket_rows=int(bucket_rows),
                           source="static-plan", data_shards=shards,
                           shardable_nbytes=shardable,
                           gather_nbytes=gather)
    item_b = _probe_item_nbytes(fitted, sample_struct)
    return ModelCharge(model_nbytes=model_b, item_nbytes=item_b,
                       bucket_rows=int(bucket_rows), source="probed",
                       data_shards=shards, shardable_nbytes=shardable,
                       gather_nbytes=gather)


@guarded_by("_lock", "_charges")
class ResidencyLedger:
    """Charged-bytes accounting for warm served models. Every mutation
    runs under ``_lock`` (declared, so the concurrency passes check
    it); :meth:`admit` re-checks the budget and charges in one lock
    hold, raising :class:`AdmissionError` without mutating when the
    charge would not fit. The plan-evict-charge SEQUENCE is serialized
    by the owning plane's lock (``serving/plane.py``) — this ledger is
    the accounting backstop, not the planner."""

    def __init__(self, budget: Optional[float]):
        self.budget = None if budget is None else float(budget)
        self._charges: Dict[str, float] = {}
        self._lock = TracedLock("serving.residency")

    def used(self) -> float:
        with self._lock:
            return sum(self._charges.values())

    def charge_of(self, name: str) -> float:
        with self._lock:
            return self._charges.get(name, 0.0)

    def resident(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._charges)

    def admit(self, name: str, nbytes: float) -> None:
        """Charge ``nbytes`` for ``name`` after re-checking the budget
        under the ledger lock; raises :class:`AdmissionError` (and
        mutates NOTHING) when the result would exceed it. Eviction
        releases happen via :meth:`release` BEFORE this call, all
        under the plane lock — so a refusal here means the planner's
        arithmetic was wrong, and it leaves the victims released and
        the newcomer uncharged (a consistent, conservative state)."""
        nbytes = float(nbytes)
        with self._lock:
            after = dict(self._charges)
            used = sum(after.values())
            if self.budget is not None and used + nbytes > self.budget:
                mib = 1 << 20
                raise AdmissionError(
                    f"admitting {name!r} ({nbytes / mib:.2f} MiB) would "
                    f"put serving residency at {(used + nbytes) / mib:.2f}"
                    f" MiB > budget {self.budget / mib:.2f} MiB "
                    f"(resident: {sorted(after) or 'none'})")
            after[name] = nbytes
            self._charges = after
        self._publish()

    def release(self, name: str) -> float:
        with self._lock:
            freed = self._charges.pop(name, 0.0)
        self._publish()
        return freed

    def _publish(self) -> None:
        # gauges are published OUTSIDE the ledger lock: the metrics
        # layer takes its own plain locks and the scrape surface only
        # needs eventually-fresh totals
        from ..observability.metrics import MetricsRegistry

        reg = MetricsRegistry.get_or_create()
        reg.gauge("serving.hbm_charged_bytes").set(self.used())
        if self.budget is not None:
            reg.gauge("serving.hbm_budget_bytes").set(self.budget)
