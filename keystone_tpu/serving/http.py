"""The HTTP surface of the serving plane + the ``serve`` CLI.

Endpoints (stdlib ``ThreadingHTTPServer``, same machinery as the PR 8
scrape endpoint — one server carries both the data plane and the
telemetry plane):

* ``POST /predict/<model>`` — body ``{"instances": [...]}`` (or a bare
  JSON array). Instances are item-shaped rows for the admitted sample;
  the handler thread submits them as ONE request to the micro-batcher
  and blocks on the future, so concurrent requests coalesce into
  padded-bucket batches. Response: ``{"model", "rows", "predictions"}``
  plus an ``X-Keystone-Trace`` header echoing the request's trace id
  (PR 16) — the handle a client quotes when it asks "where did my
  2-second request spend its time".
  Errors map to honest statuses: 404 unknown model, 503 warming,
  429 bounded-queue full (with a drain-rate ``Retry-After`` header),
  504 deadline shed (an optional ``deadline_ms`` body key bounds how
  long the request may queue before dispatch), 400 shape/JSON errors,
  500 batch failure (a poisoned batch names its post-mortem artifact
  in the error body).
* ``GET /healthz`` — the REAL readiness gate: 503 ``warming`` until
  every admitted model's warmup compile completed
  (``ServingPlane.ready`` via the ``serve_metrics`` ready-probe).
* ``GET /metrics`` — Prometheus text exposition of the process
  registry (``serving.*`` families included).
* ``GET /models`` — JSON plane state (residency charges, buckets,
  per-model QPS, evicted set).
* ``GET /slo`` — the SLO tracker's state: policy, rolling
  availability / burn rate (aggregate + per model), lifetime totals,
  and the bounded violation log with post-mortem paths.
* ``GET /debug/slow?n=8[&model=m]`` — the slowest retained request
  span trees from the exemplar reservoir (trace id, per-phase ms,
  batch membership) — the "show me the tail" endpoint.

CLI::

    python -m keystone_tpu serve NAME=PATH@SHAPE[:DTYPE] ... \
        [--port P] [--host H] [--hbm-budget BYTES] [--max-batch N] \
        [--queue-depth N] [--weight-dtype bf16|int8|f32] \
        [--drift-every N] [--slo-latency-ms MS] [--slo-availability A]

``SHAPE`` is the per-item shape (comma-separated, e.g. ``784`` or
``32,32,3``), ``DTYPE`` defaults to float32. The server binds BEFORE
admitting (so ``/healthz`` observably reports warming during the
warmup compiles), prints ``serving on HOST:PORT`` then
``serving ready (N models)`` — the lines the CI gate
(``tools/serving_gate.py``) parses. ``--weight-dtype`` defaults to
bf16: the PR 13 quantized predict is the serving default; pass ``f32``
to opt out.
"""
from __future__ import annotations

import json
import math
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..observability.metrics import MetricsRegistry
from ..observability.reqtrace import exemplar_reservoir
from ..observability.sampler import _MetricsHandler, _MetricsServer
from ..observability.slo import SloPolicy
from ..utils.guarded import hotpath
from .batcher import DeadlineExpiredError, QueueFullError
from .plane import ModelNotAdmitted, ModelWarming, ServingPlane
from .residency import AdmissionError


class _JsonReplyHandler(_MetricsHandler):
    """The JSON-reply half every keystone HTTP surface shares: the
    single-process serving handler below, the fleet router's
    forwarding handler (``serving/router.py``), and the replica admin
    surface (``serving/replica.py``) all speak through this one
    ``_reply`` — same headers, same framing, one allowlisted hot-path
    write."""

    def _reply(self, status: int, body: bytes,
               ctype: str = "application/json",
               headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)


def predict_response(plane: Any, name: str, raw: bytes
                     ) -> Tuple[int, bytes, Optional[Dict[str, str]]]:
    """One predict call against ``plane``, mapped to the HTTP verdict:
    ``(status, body, extra headers)``. This is THE shared request-path
    code the plane split exists for — the single-process handler below
    and the fleet router's local replica client both run exactly this
    function, so every serving surface maps the exception family to the
    same honest statuses (404 unknown / 503 warming / 504 shed /
    429-with-Retry-After full / 400 bad shape / 500 batch failure)."""
    try:
        blob = json.loads(raw or b"null")
        instances = (blob.get("instances")
                     if isinstance(blob, dict) else blob)
        deadline_ms = (blob.get("deadline_ms")
                       if isinstance(blob, dict) else None)
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
            if deadline_ms <= 0:
                raise ValueError("deadline_ms must be > 0")
        if not isinstance(instances, list) or not instances:
            raise ValueError(
                'body must be {"instances": [...]} or a JSON array')
        out, trace_id = plane.predict_traced(
            name, np.asarray(instances), deadline_ms=deadline_ms)
        body = json.dumps({
            "model": name,
            "rows": len(instances),
            "predictions": _jsonable(out),
        }).encode()
        # the trace id rides a header, not the body — existing
        # clients keep parsing the same JSON shape
        headers = {"X-Keystone-Trace": trace_id} if trace_id else None
        return 200, body, headers
    except ModelNotAdmitted as exc:
        return 404, _err(exc), None
    except ModelWarming as exc:
        return 503, _err(exc), None
    except DeadlineExpiredError as exc:
        # the request was shed before dispatch: the honest verdict
        # is "too late", not "server broke" — 504, like a gateway
        # giving up on an upstream budget
        return 504, _err(exc), None
    except QueueFullError as exc:
        # sustained overload answers WHEN, not just no: the header
        # carries the batcher's drain-rate estimate (integer
        # seconds per RFC 9110, floored at 1)
        return 429, _err(exc), {
            "Retry-After": str(max(1, math.ceil(exc.retry_after_s)))}
    except (ValueError, TypeError, json.JSONDecodeError) as exc:
        return 400, _err(exc), None
    except Exception as exc:  # batch execution failure: honest 500
        return 500, _err(exc), None


class ServingHandler(_JsonReplyHandler):
    """Extends the metrics/healthz handler with the predict data plane
    (``plane`` is bound per server by :func:`serve`)."""

    plane: Optional[ServingPlane] = None

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        from ..observability.timeline import flight_recorder

        # scrape-time flush point: materialize the worker's deferred
        # telemetry (spans + phase observes) before serializing any view
        flight_recorder().flush()
        parts = urlsplit(self.path)
        if parts.path == "/models":
            self._reply(200, json.dumps(self.plane.state()).encode(),
                        "application/json")
            return
        if parts.path == "/slo":
            self._reply(200,
                        json.dumps(self.plane.slo.state()).encode(),
                        "application/json")
            return
        if parts.path == "/debug/slow":
            try:
                query = parse_qs(parts.query)
                n = int(query.get("n", ["8"])[0])
                model = query.get("model", [None])[0]
            except (ValueError, TypeError) as exc:
                self._reply(400, _err(exc))
                return
            body = json.dumps({"slowest": exemplar_reservoir()
                               .slowest_trees(n, model=model)}).encode()
            self._reply(200, body, "application/json")
            return
        super().do_GET()

    @hotpath
    def do_POST(self):  # noqa: N802 (stdlib handler API)
        path = self.path.split("?")[0]
        if not path.startswith("/predict/"):
            self._reply(404, b'{"error": "unknown endpoint"}\n')
            return
        name = path[len("/predict/"):]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
        except (ValueError, TypeError) as exc:
            self._reply(400, _err(exc))
            return
        status, body, headers = predict_response(self.plane, name, raw)
        self._reply(status, body, "application/json", headers=headers)


def _err(exc: BaseException) -> bytes:
    return json.dumps({"error": f"{type(exc).__name__}: {exc}"}).encode()


def _jsonable(out: Any) -> Any:
    if isinstance(out, np.ndarray):
        return out.tolist()
    if isinstance(out, (list, tuple)):
        return [_jsonable(o) for o in out]
    if isinstance(out, dict):
        return {k: _jsonable(v) for k, v in out.items()}
    if hasattr(out, "tolist"):
        return out.tolist()
    return out


def bind_server(handler_cls: type, attrs: Dict[str, Any],
                port: int = 0, host: str = "127.0.0.1",
                thread_name: str = "keystone-http") -> _MetricsServer:
    """Bind a per-instance subclass of ``handler_cls`` (class attrs in
    ``attrs``, e.g. the plane/registry/ready probe) on ``host:port``
    and serve it from a daemon thread. The one server-construction
    idiom every serving surface uses — single-process plane, fleet
    router, replica admin — so shutdown/join semantics stay uniform
    (``.shutdown()`` joins the thread and releases the port)."""
    handler = type("_Bound" + handler_cls.__name__, (handler_cls,),
                   dict(attrs))
    server = _MetricsServer((host, port), handler)
    t = threading.Thread(target=server.serve_forever,
                         name=thread_name, daemon=True)
    server._keystone_thread = t
    t.start()
    return server


def serve(plane: ServingPlane, port: int = 0, host: str = "127.0.0.1",
          registry: Optional[MetricsRegistry] = None) -> _MetricsServer:
    """Bind the serving endpoints for ``plane`` on ``host:port``
    (``port=0`` = ephemeral; read ``server.server_port`` back) and
    start serving from a daemon thread. ``/healthz`` is readiness-gated
    on ``plane.ready``. Returns the server; ``.shutdown()`` releases
    the port."""
    return bind_server(
        ServingHandler,
        {"registry": registry, "plane": plane,
         "ready_probe": staticmethod(plane.ready)},
        port=port, host=host, thread_name="keystone-serving-http")


# -- CLI ----------------------------------------------------------------------

def _parse_model_spec(spec: str):
    """``NAME=PATH@SHAPE[:DTYPE]`` -> (name, path, shape tuple, dtype)."""
    if "=" not in spec or "@" not in spec:
        raise ValueError(
            f"model spec {spec!r} must look like "
            "NAME=PATH@SHAPE[:DTYPE] (e.g. mnist=model.pkl@784:float32)")
    name, rest = spec.split("=", 1)
    path, shape_spec = rest.rsplit("@", 1)
    dtype = "float32"
    if ":" in shape_spec:
        shape_spec, dtype = shape_spec.split(":", 1)
    shape = tuple(int(d) for d in shape_spec.split(",") if d)
    return name, path, shape, np.dtype(dtype)


def _pop_flag(argv: List[str], flag: str,
              default: Optional[str] = None) -> Optional[str]:
    if flag not in argv:
        return default
    i = argv.index(flag)
    if i + 1 >= len(argv):
        raise ValueError(f"{flag} requires a value")
    value = argv[i + 1]
    del argv[i:i + 2]
    return value


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m keystone_tpu serve`` — see the module docstring."""
    import jax

    from ..__main__ import _parse_bytes
    from ..utils.checkpoint import load_pipeline

    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        port = int(_pop_flag(argv, "--port", "9100"))
        host = _pop_flag(argv, "--host", "127.0.0.1")
        budget_text = _pop_flag(argv, "--hbm-budget")
        budget = None if budget_text is None else _parse_bytes(budget_text)
        max_batch = int(_pop_flag(argv, "--max-batch", "64"))
        queue_depth = int(_pop_flag(argv, "--queue-depth", "256"))
        wd = _pop_flag(argv, "--weight-dtype", "bf16")
        weight_dtype = None if wd in ("f32", "none", "f32/none") else wd
        drift_every = int(_pop_flag(argv, "--drift-every", "32"))
        slo_latency = _pop_flag(argv, "--slo-latency-ms")
        slo_avail = _pop_flag(argv, "--slo-availability")
        slo_policy = None
        if slo_latency is not None or slo_avail is not None:
            kwargs = {}
            if slo_latency is not None:
                kwargs["latency_threshold_ms"] = float(slo_latency)
            if slo_avail is not None:
                kwargs["availability_target"] = float(slo_avail)
            slo_policy = SloPolicy(**kwargs)
        specs = [_parse_model_spec(s) for s in argv if not
                 s.startswith("-")]
    except ValueError as exc:
        print(f"serve: {exc}", file=sys.stderr)
        return 2
    if not specs:
        print("usage: python -m keystone_tpu serve "
              "NAME=PATH@SHAPE[:DTYPE] ... [--port P] [--host H] "
              "[--hbm-budget BYTES] [--max-batch N] [--queue-depth N] "
              "[--weight-dtype bf16|int8|f32] [--drift-every N] "
              "[--slo-latency-ms MS] [--slo-availability A]",
              file=sys.stderr)
        return 2

    plane = ServingPlane(
        hbm_budget=budget, max_batch=max_batch, queue_depth=queue_depth,
        default_weight_dtype=weight_dtype, drift_every=drift_every,
        slo_policy=slo_policy)
    # readiness waits for every listed model BEFORE the port opens:
    # a scrape between bind and the last warmup sees 503 warming
    plane.expect_models(len(specs))
    plane.start()
    server = serve(plane, port=port, host=host)
    print(f"serving on {host}:{server.server_port}", flush=True)
    try:
        for name, path, shape, dtype in specs:
            fitted = load_pipeline(path)
            entry = plane.admit(
                name, fitted, jax.ShapeDtypeStruct(shape, dtype))
            mib = 1 << 20
            print(f"admitted {name!r}: "
                  f"{entry.charge.total_nbytes() / mib:.2f} MiB "
                  f"({entry.charge.source}), buckets "
                  f"{list(entry.buckets)}, warmup "
                  f"{entry.warmup_s:.2f}s, weight_dtype "
                  f"{entry.weight_dtype or 'f32'}", flush=True)
        print(f"serving ready ({len(specs)} models) on "
              f"{host}:{server.server_port}", flush=True)
        threading.Event().wait()  # serve until interrupted
    except AdmissionError as exc:
        print(f"serve: admission refused: {exc}", file=sys.stderr)
        return 3
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        plane.close()
    return 0
