"""The fleet placement solver: bin-pack models across replicas.

The third generalization of the auto-cache greedy
(``workflow/optimizer/auto_cache.py:greedy_select`` — first profiles
under a cache budget, then plane evictions under the HBM budget, now
fleet placement): given per-model demands (admission charge, observed
QPS, warmup recompute cost) and per-replica HBM budgets, produce a
deterministic assignment of models to replicas that

1. **single-homes every model** — first-fit-decreasing by charge onto
   the least-loaded replica that fits (the classic bin-packing
   heuristic, 11/9-OPT bounded), refusing LOUDLY (the error names the
   model) when nothing fits anywhere; then
2. **replicates hot models** for throughput — per replica, a
   value-maximizing ``greedy_select`` over the models it does not yet
   host, value = observed QPS x warmup (recompute) cost diminished by
   the copies already placed: the same LRU-with-cost currency the
   plane's eviction planner spends, so placement and eviction argue
   about the same quantity.

Inputs all exist in the tree: the charge is the static planner's
``model_nbytes + bucket x apply_item_nbytes`` bound
(``serving/residency.py`` / ``analysis/resources.py``, including the
PR 18 ``sharded_apply_nbytes`` arithmetic for over-one-host models via
``data_shards``), QPS comes from the scraped ``ServedModel.qps()`` /
loadgen surface, warmup from the measured admission wall.

Everything here is pure host-side arithmetic — deterministic under
fixed inputs (pinned by ``tests/test_placement.py``), no RNG, no wall
clock — so the fleet controller can re-solve on every reactor tick and
diff against the live placement to plan migrations.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from ..observability.metrics import MetricsRegistry


class PlacementError(RuntimeError):
    """No replica can host the named model under its HBM budget — the
    refusal names the model (never a silent drop)."""

    def __init__(self, message: str, model: Optional[str] = None):
        super().__init__(message)
        self.model = model


@dataclass(frozen=True)
class ModelDemand:
    """One model's placement inputs: the admission charge it costs a
    replica, and the demand (QPS x warmup) that justifies copies."""

    name: str
    charge_nbytes: float
    qps: float = 0.0
    warmup_s: float = 0.0

    def __post_init__(self):
        if self.charge_nbytes < 0:
            raise ValueError(
                f"model {self.name!r}: charge_nbytes must be >= 0")
        if self.qps < 0:
            raise ValueError(f"model {self.name!r}: qps must be >= 0")

    def value(self, copies: int = 0) -> float:
        """Marginal value of one MORE copy given ``copies`` already
        placed: QPS x recompute cost, halved per existing copy (the
        second replica absorbs half the traffic the first did). Zero
        for a cold model — replication is bought with observed demand,
        never speculation."""
        if self.qps <= 0.0:
            return 0.0
        return (self.qps * max(self.warmup_s, 1e-3)) / float(1 + copies)


@dataclass(frozen=True)
class Placement:
    """A solved fleet assignment: ``assignments[model]`` is the sorted
    tuple of replica ids hosting it (first entry = the single-homing
    choice), ``loads[replica]`` the charged bytes."""

    assignments: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    loads: Dict[str, float] = field(default_factory=dict)

    def replicas_for(self, model: str) -> Tuple[str, ...]:
        return self.assignments.get(model, ())

    def models_on(self, replica: str) -> Tuple[str, ...]:
        return tuple(sorted(m for m, reps in self.assignments.items()
                            if replica in reps))

    def copies(self) -> int:
        return sum(len(reps) for reps in self.assignments.values())

    def diff(self, target: "Placement"
             ) -> List[Tuple[str, str, str]]:
        """Migration steps from this placement to ``target``:
        ``("admit", model, replica)`` / ``("evict", model, replica)``
        tuples, admissions first (the migration contract: admit on the
        target, VERIFY, then evict on the source — capacity is briefly
        double-charged, never zero-charged)."""
        steps: List[Tuple[str, str, str]] = []
        models = sorted(set(self.assignments) | set(target.assignments))
        for model in models:
            have = set(self.assignments.get(model, ()))
            want = set(target.assignments.get(model, ()))
            for replica in sorted(want - have):
                steps.append(("admit", model, replica))
        for model in models:
            have = set(self.assignments.get(model, ()))
            want = set(target.assignments.get(model, ()))
            for replica in sorted(have - want):
                steps.append(("evict", model, replica))
        return steps


def plan_placement(demands: Iterable[ModelDemand],
                   replica_budgets: Mapping[str, Optional[float]],
                   ) -> Placement:
    """Solve a fleet placement; see module docstring. ``None`` budgets
    are unbounded (every model fits). Raises :class:`PlacementError`
    naming the first model no replica can host. Deterministic: ties
    break by sorted name order, never by dict/hash order."""
    demands = sorted(demands, key=lambda d: d.name)
    if len({d.name for d in demands}) != len(demands):
        raise ValueError("duplicate model names in placement demands")
    if not replica_budgets:
        raise ValueError("placement needs at least one replica")
    replicas = sorted(replica_budgets)
    loads: Dict[str, float] = {r: 0.0 for r in replicas}
    assignments: Dict[str, List[str]] = {}

    def fits(replica: str, charge: float) -> bool:
        budget = replica_budgets[replica]
        return budget is None or loads[replica] + charge <= budget

    # -- phase 1: single-home, first-fit-decreasing by charge ---------------
    # big models place first (small ones fill the gaps they leave);
    # equal charges break by name, equal loads by replica id — the
    # whole solve is reproducible from its inputs alone
    for demand in sorted(demands,
                         key=lambda d: (-d.charge_nbytes, d.name)):
        eligible = [r for r in replicas if fits(r, demand.charge_nbytes)]
        if not eligible:
            MetricsRegistry.get_or_create().counter(
                "placement.infeasible_total").inc()
            mib = 1 << 20
            budgets = {r: (None if b is None else round(b / mib, 2))
                       for r, b in sorted(replica_budgets.items())}
            raise PlacementError(
                f"model {demand.name!r} "
                f"({demand.charge_nbytes / mib:.2f} MiB) fits no "
                f"replica: remaining capacity under budgets (MiB) "
                f"{budgets} is exhausted — add a replica, raise a "
                "budget, or shrink/quantize the model",
                model=demand.name)
        home = min(eligible, key=lambda r: (loads[r], r))
        assignments[demand.name] = [home]
        loads[home] += demand.charge_nbytes

    # -- phase 2: replicate hot models into leftover capacity ---------------
    # per replica (sorted — determinism again), a value-maximizing
    # greedy_select over the models it does not yet host; the marginal
    # value halves per copy already placed, so two equally hot models
    # replicate evenly instead of one hogging every replica
    from ..workflow.optimizer.auto_cache import greedy_select

    by_name = {d.name: d for d in demands}
    for replica in replicas:
        budget = replica_budgets[replica]
        if budget is None:
            # unbounded replicas don't replicate speculatively: with no
            # scarcity there is no placement question to answer, and
            # admitting every model everywhere just multiplies warmups
            continue
        remaining = budget - loads[replica]
        if remaining <= 0.0:
            continue
        resident = {m for m, reps in assignments.items()
                    if replica in reps}

        def candidates(selected, space_left,
                       _resident=resident):
            # gate cold models out HERE: greedy_select has no
            # improvement check, so a zero-value candidate would be
            # packed anyway just because it fits
            return [n for n in sorted(by_name)
                    if n not in _resident and n not in selected
                    and by_name[n].value(len(assignments[n])) > 0.0
                    and by_name[n].charge_nbytes < space_left]

        chosen = greedy_select(
            (), candidates,
            lambda n: by_name[n].charge_nbytes,
            lambda sel: -sum(by_name[n].value(len(assignments[n]))
                             for n in sel),
            remaining)
        for name in sorted(chosen):
            assignments[name].append(replica)
            loads[replica] += by_name[name].charge_nbytes

    placement = Placement(
        assignments={m: tuple(sorted(reps))
                     for m, reps in assignments.items()},
        loads=dict(loads))
    reg = MetricsRegistry.get_or_create()
    reg.counter("placement.solves_total").inc()
    reg.gauge("placement.replicated_models").set(
        sum(1 for reps in placement.assignments.values()
            if len(reps) > 1))
    return placement
