"""The serving plane: warm multi-model residency + the batch worker.

``ServingPlane`` is the process-level object behind
``python -m keystone_tpu serve``: fitted pipelines are ADMITTED into it
(charged against the HBM budget, warmed bucket by bucket), requests are
SUBMITTED to it (micro-batched behind the bounded queue), and the whole
thing reports through the existing funnels — nothing here invents a new
telemetry channel:

* **Warm executables.** Admission warms every request bucket (full AND
  partial fill, so the mask program compiles too) before the model is
  marked ready; the compiled programs live in the same global caches
  every pipeline apply uses (``_JIT_CACHE`` / ``_VMAP_JIT_CACHE``,
  keyed on eq/struct keys), so steady-state requests re-dispatch warm
  XLA executables. After warmup the PR 9 observatory fence stays armed
  (``serving:steady-state``): any runtime compile is counted in
  ``compile.unexpected_total`` — zero steady-state recompiles per
  request shape is an asserted invariant, not a hope (PERFORMANCE.md
  rule 14).
* **Admission control.** The charge is the static planner's
  ``model_nbytes + bucket x apply_item_nbytes`` bound
  (``serving/residency.py``); placement/eviction under the budget
  reuses the auto-cache profile-under-budget greedy
  (``workflow/optimizer/auto_cache.py:greedy_select``) with
  LRU-with-cost retention value: observed QPS x recompute (warmup)
  cost, recency as the tiebreak. Evicted models keep their canonical
  pickled bytes host-side, so eviction + readmission round-trips to
  bit-identical predictions.
* **Observability.** Per-model ``serving.request_ms.<model>`` /
  ``serving.batch_fill.<model>`` histograms (plus the aggregate
  families) land in the PR 8 registry and scrape surface; every
  ``drift_every`` batches a model with a fit-time sketch
  (``model.numerics_baseline``, PR 10) has its live inputs scored via
  ``score_drift`` — a stale model trips ``numerics.drift_warn`` before
  its accuracy visibly drops. The PR 13 ``weight_dtype`` bf16/int8
  quantized predict is applied at admission when requested (the serve
  CLI defaults to bf16). PR 16 added the request path itself: each
  request carries a :class:`~keystone_tpu.observability.reqtrace.\
ReqTrace` whose phase stamps (queue_wait / coalesce / dispatch /
  respond) telescope exactly to ``serving.request_ms``, feed the
  ``serving.phase_ms.<phase>`` histograms, link into per-batch flow
  spans on the flight recorder, fill the slowest-N exemplar reservoir,
  and drive the rolling-window SLO tracker (``self.slo``) — one
  post-mortem per violated availability window.

Thread model: handler/caller threads run ``admit``/``submit``;
``workers`` worker threads (default 1, ``KEYSTONE_SERVE_WORKERS``)
drain the batcher. ``_models``/``_evicted``/
``_warming``/``_expected`` are ``@guarded_by`` the plane lock; device
work (warmup, batch execution) always runs OUTSIDE it.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..observability.metrics import MetricsRegistry
from ..observability.reqtrace import (PHASES, exemplar_reservoir,
                                      mint_flow_id)
from ..observability.timeline import flight_recorder
from ..parallel.dataset import ArrayDataset, Dataset, bucketed_dataset
from ..resilience.faults import corrupt, inject
from ..utils.guarded import TracedLock, guarded_by, hotpath, published_by
from .batcher import (BucketPolicy, DeadlineExpiredError, MicroBatcher,
                      Request)
from .residency import AdmissionError, ResidencyLedger, model_charge


class ModelNotAdmitted(LookupError):
    """The named model is not resident (never admitted, or evicted)."""


class ModelWarming(RuntimeError):
    """The named model is admitted but its warmup has not completed —
    retry after ``/healthz`` reports ready."""


class PoisonedBatchError(RuntimeError):
    """A dispatched batch came back with non-finite outputs (NaN born
    between enqueue and collect — a poisoned input, or a numeric
    breakdown in the model). Exactly this batch's requests fail
    (classified 500, post-mortem attached); the worker and the queue
    survive to serve the next batch."""


# the model-record layer lives in serving/models.py since the fleet
# split (placement/fleet import the records without the whole plane);
# re-exported here because this module IS the historical home of these
# names (tests and callers import them from serving.plane)
from .models import (_QPS_WINDOW_S, ServedModel, _EvictedModel,  # noqa: F401
                     _apply_weight_dtype, _count_nonfinite,
                     _evicted_record, _find_baseline, _zeros_batch)


@published_by("_lock", "_live")
@guarded_by("_lock", "_models", "_evicted", "_warming", "_expected",
            "_admitted_total")
class ServingPlane:
    """Warm multi-model serving under an HBM budget; see module
    docstring. Usable as a context manager (``close`` disarms the
    steady-state fence and stops the worker).

    ``_live`` is the PUBLISHED ready-model snapshot: a fresh dict
    rebuilt and rebound in one reference flip by
    :meth:`_publish_locked` every time residency changes, read
    LOCK-FREE by :meth:`submit_request`'s fast path — the same swap
    discipline ROADMAP item 1's versioned hot-swap must follow (the
    publication pass in ``analysis/hotpath.py`` checks it)."""

    def __init__(self, hbm_budget: Optional[float] = None,
                 max_batch: int = 64, queue_depth: int = 128,
                 default_weight_dtype: Optional[str] = None,
                 drift_every: int = 32,
                 policy: Optional[BucketPolicy] = None,
                 mesh: Any = None, steady_fence: bool = True,
                 slo_policy: Any = None, data_shards: int = 1,
                 nonfinite_guard: bool = True,
                 postmortem_min_interval_s: float = 30.0,
                 workers: Optional[int] = None):
        from ..observability.slo import SloTracker
        from ..parallel.mesh import get_mesh, num_data_shards

        self.mesh = mesh or get_mesh()
        self._shards = num_data_shards(self.mesh)
        #: >1 opts admission into the sharded-apply charge arithmetic
        #: (parallel/spmd_apply.py): ``hbm_budget`` then reads as ONE
        #: HOST's budget, each model's shardable fitted state divides
        #: across the data axis, and a model whose total model_nbytes
        #: exceeds the per-host budget can still be placed (CLUSTER.md
        #: "Serving topology"). 1 (default) keeps the replicated
        #: single-host charge.
        self.data_shards = max(int(data_shards), 1)
        self.policy = policy or BucketPolicy(max_batch)
        self.ledger = ResidencyLedger(hbm_budget)
        self.batcher = MicroBatcher(queue_depth)
        #: rolling-window error-budget accounting (PR 16); fed one
        #: outcome per request by the worker, read by ``GET /slo``
        self.slo = SloTracker(slo_policy)
        self.drift_every = max(int(drift_every), 1)
        self.default_weight_dtype = default_weight_dtype
        self.steady_fence = steady_fence
        #: fail a batch whose outputs carry NaN/inf instead of handing
        #: clients silently-poisoned predictions (PoisonedBatchError)
        self.nonfinite_guard = bool(nonfinite_guard)
        #: at most one batch-failure post-mortem per this many seconds
        #: (a chaos storm must not write one artifact per failed batch;
        #: the scenario harness sets 0 to capture every failure)
        self.postmortem_min_interval_s = float(postmortem_min_interval_s)
        self._last_batch_pm_s = -1e18
        self._models: Dict[str, ServedModel] = {}
        #: published lock-free snapshot of the READY residents; only
        #: ever rebound whole under the lock (_publish_locked / close)
        self._live: Dict[str, ServedModel] = {}
        self._evicted: Dict[str, _EvictedModel] = {}
        self._warming = 0
        self._expected = 0
        self._admitted_total = 0
        self._fence_armed = False
        self._lock = TracedLock("serving.plane")
        self._stop = threading.Event()
        #: dispatch concurrency: N worker threads drain the batcher
        #: concurrently (JAX dispatch releases the GIL, so batches for
        #: different models genuinely overlap). Default 1 preserves the
        #: single-worker semantics exactly; the KEYSTONE_SERVE_WORKERS
        #: env var is the fleet-deployment knob (PERFORMANCE.md rule 19
        #: — measure serving.queue_wait_s before reaching for it).
        if workers is None:
            workers = int(os.environ.get("KEYSTONE_SERVE_WORKERS",
                                         "1") or "1")
        self.workers = max(int(workers), 1)
        self._worker: Optional[threading.Thread] = None
        self._workers: List[threading.Thread] = []
        # the serving thread's identity, cached once at worker start so
        # the per-batch defer does not pay a current_thread() lookup
        # (defaults cover tests driving _serve_batch directly)
        self._worker_tid = 0
        self._worker_name = "serving-worker"
        self._closed = False
        # per-model phase-histogram handles, resolved once per model:
        # the per-request hot loop must not pay a registry lookup per
        # observe (the always-on <2% bar, PERFORMANCE.md rule 15);
        # keyed off the live registry so a test-harness reset
        # invalidates the cache instead of feeding a dead registry
        self._phase_reg: Any = None
        self._phase_hists: Dict[str, Dict[str, Tuple[Any, Any]]] = {}
        if hbm_budget is not None:
            MetricsRegistry.get_or_create().gauge(
                "serving.hbm_budget_bytes").set(float(hbm_budget))

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "ServingPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def start(self) -> "ServingPlane":
        """Start the batch worker(s) (idempotent)."""
        with self._lock:
            if self._worker is None and not self._closed:
                self._stop = threading.Event()
                for i in range(self.workers):
                    t = threading.Thread(
                        target=self._worker_loop, args=(i == 0,),
                        name=("keystone-serving-worker" if i == 0
                              else f"keystone-serving-worker-{i}"),
                        daemon=True)
                    self._workers.append(t)
                self._worker = self._workers[0]
                for t in self._workers:
                    t.start()
        return self

    def close(self) -> None:
        """Stop the worker, fail queued requests loudly, and disarm the
        steady-state fence (a long-lived armed fence would mislabel the
        process's later compiles as serving recompiles)."""
        with self._lock:
            self._closed = True
            # atomic flip: lock-free submitters fall to the locked slow
            # path, which sees _closed and the batcher refusal
            self._live = {}
            workers = list(self._workers)
            self._workers = []
            self._worker = None
            self._stop.set()
            if self._fence_armed:
                self._fence_armed = False
                self._observatory().disarm_fence()
        for worker in workers:
            worker.join(timeout=10.0)
        for req in self.batcher.close():
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("serving plane closed"))
        # the worker is gone; materialize whatever it deferred so
        # post-shutdown artifact dumps see every span/observe
        flight_recorder().flush()

    @staticmethod
    def _observatory():
        from ..observability.compilelog import compile_observatory

        return compile_observatory()

    # -- readiness ---------------------------------------------------------
    def expect_models(self, count: int) -> None:
        """Declare how many admissions readiness must wait for — the
        serve CLI calls this BEFORE binding the port, so ``/healthz``
        reports not-ready from the first byte until the last admitted
        model finished warming (the readiness-gate contract)."""
        with self._lock:
            self._expected = max(int(count), 0)

    def ready(self) -> bool:
        """True when every admitted model's warmup compile completed
        and at least ``expect_models`` admissions have COMPLETED.
        Completed is counted cumulatively (``_admitted_total``), not as
        current residents: a startup admission that evicts an earlier
        model must not wedge readiness at 503 forever (review
        finding)."""
        with self._lock:
            return self._ready_locked()

    def _ready_locked(self) -> bool:
        """The readiness verdict with the lock already held — shared by
        :meth:`ready` and :meth:`state` so a ``/models`` body can never
        pair a ready=True verdict with a model list from a different
        instant (the churn-scenario race)."""
        return (self._warming == 0
                and self._admitted_total >= self._expected
                and all(e.ready for e in self._models.values()))

    # -- admission ---------------------------------------------------------
    def admit(self, name: str, fitted: Any, sample: Any,
              weight_dtype: Optional[str] = "default") -> ServedModel:
        """Admit a fitted pipeline as a warm served model.

        ``sample`` describes ONE request item (array, ShapeDtypeStruct,
        or ``(shape, dtype)``). The pipeline is canonicalized through a
        pickle round-trip (the caller's object is never mutated), the
        requested ``weight_dtype`` (``"default"`` = the plane default)
        is applied to quantizable mappers, the admission charge is
        derived from the static plan, budget space is made by
        LRU-with-cost eviction where allowed, and every bucket is
        warmed before the model turns ready. Raises
        :class:`~.residency.AdmissionError` — mutating nothing — when
        the model cannot fit even after every allowed eviction."""
        sample = self._as_sample_struct(sample)
        wd = (self.default_weight_dtype if weight_dtype == "default"
              else weight_dtype)
        try:
            working = pickle.loads(pickle.dumps(fitted))
        except Exception as exc:
            raise TypeError(
                f"model {name!r} is not picklable ({exc}) — serving "
                "keeps a canonical pickled copy so eviction/readmission "
                "round-trips bit-identically (the same constraint "
                "utils.checkpoint.save_pipeline imposes). Replace "
                "closures/lambdas in the pipeline with named "
                "module-level functions or Transformer subclasses."
            ) from exc
        # normalize to a Pipeline so .apply means dataset-bind (a bare
        # fitted Transformer from fit_streaming reserves .apply for its
        # per-item function); the mutated operators are SHARED with
        # `working`, so the canonical blob below carries the applied
        # weight_dtype and readmission round-trips bit-identically
        pipeline = working.to_pipeline()
        _apply_weight_dtype(pipeline.graph, wd)
        blob = pickle.dumps(working)
        buckets = self.policy.rows(self._shards)
        charge = model_charge(pipeline, sample, buckets[-1], name=name,
                              data_shards=self.data_shards)
        entry = ServedModel(
            name=name, fitted=pipeline, blob=blob, sample=sample,
            charge=charge, buckets=buckets, weight_dtype=wd,
            baseline=_find_baseline(pipeline.graph))
        # fault site BEFORE any plane mutation: an injected admission
        # fault here refuses atomically (nothing registered, nothing
        # evicted); faults MID-warmup fire per bucket inside _warm and
        # roll back through _finish_warmup instead
        inject("serve.admit", context=name)
        with self._lock:
            if self._closed:
                raise RuntimeError("serving plane closed")
            if name in self._models:
                raise ValueError(f"model {name!r} is already admitted")
            victims = self._plan_evictions_locked(charge.total_nbytes())
            for victim in victims:
                dropped = self._models.pop(victim)
                self.ledger.release(victim)
                self._evicted[victim] = _evicted_record(dropped)
                # drop the victim's cached phase-histogram handles too:
                # admit/evict churn must not leak one entry per model
                # name ever served (hotpath-unbounded-growth finding)
                self._phase_hists.pop(victim, None)
            # the backstop: the ledger re-checks atomically and raises
            # without mutating if the plan raced anything
            self.ledger.admit(name, charge.total_nbytes())
            self._models[name] = entry
            # a readmitted name leaves the evicted set: its stale blob
            # must not shadow the live entry in /models or stay
            # host-resident forever (review finding); kept aside so a
            # FAILED warmup can restore it instead of losing the model
            prior_evicted = self._evicted.pop(name, None)
            self._warming += 1
            if self._fence_armed:
                # warmup compiles are EXPECTED: the steady-state fence
                # steps aside until every in-flight warmup completes
                self._fence_armed = False
                self._observatory().disarm_fence()
            self._publish_locked()
        try:
            t0 = time.perf_counter()
            self._warm(entry)
            entry.warmup_s = time.perf_counter() - t0
        except BaseException:
            self._finish_warmup(entry, ok=False,
                                restore_evicted=prior_evicted)
            raise
        MetricsRegistry.get_or_create().histogram(
            "serving.warmup_s").observe(entry.warmup_s)
        self._finish_warmup(entry, ok=True)
        return entry

    def _finish_warmup(self, entry: ServedModel, ok: bool,
                       restore_evicted: Optional[_EvictedModel] = None
                       ) -> None:
        """One admission's warmup epilogue: mark ready (or roll the
        registration back on failure, restoring the evicted record a
        readmission popped), leave the warming count, and re-arm the
        steady-state fence when no warmup remains in flight — one lock
        hold, so readiness and the fence can never disagree."""
        with self._lock:
            if ok:
                entry.ready = True
                self._admitted_total += 1
            else:
                self._models.pop(entry.name, None)
                self.ledger.release(entry.name)
                self._phase_hists.pop(entry.name, None)
                if restore_evicted is not None:
                    self._evicted[entry.name] = restore_evicted
            self._warming -= 1
            self._sync_fence()
            self._publish_locked()

    def evict(self, name: str) -> None:
        """Explicitly evict a resident model (its canonical bytes stay
        host-side for :meth:`readmit`). The fault site fires BEFORE the
        lock: an injected eviction fault aborts with the model fully
        resident — eviction is atomic (all mutations happen in one lock
        hold, or none happen at all)."""
        inject("serve.evict", context=name)
        with self._lock:
            if name not in self._models:
                raise ModelNotAdmitted(f"model {name!r} is not resident")
            entry = self._models.pop(name)
            self.ledger.release(name)
            self._evicted[name] = _evicted_record(entry)
            # the cached histogram handles go with the model (the leak
            # the first hotpath tree scan found: one entry per model
            # name ever served, never pruned)
            self._phase_hists.pop(name, None)
            self._publish_locked()

    def readmit(self, name: str) -> ServedModel:
        """Re-admit a previously evicted model from its canonical
        pickled bytes — the round-trip is bit-identical by construction
        (same bytes, same quantization, same programs)."""
        with self._lock:
            evicted = self._evicted.get(name)
        if evicted is None:
            raise ModelNotAdmitted(
                f"model {name!r} was never evicted from this plane")
        fitted = pickle.loads(evicted.blob)
        return self.admit(name, fitted, evicted.sample,
                          weight_dtype=evicted.weight_dtype)

    def _plan_evictions_locked(self, needed: float) -> List[str]:
        """Which ready residents to evict so ``needed`` bytes fit:
        keep the highest retention-value set that fits in the remaining
        budget (the auto-cache greedy, value-maximizing), evict the
        rest. Warming models are never victims. Raises AdmissionError
        when ``needed`` exceeds the whole budget (refusal — documented
        admission math, README "Serving")."""
        budget = self.ledger.budget
        if budget is None:
            return []
        if needed > budget:
            MetricsRegistry.get_or_create().counter(
                "serving.admission_rejected_total").inc()
            mib = 1 << 20
            raise AdmissionError(
                f"model charge {needed / mib:.2f} MiB exceeds the whole "
                f"serving HBM budget {budget / mib:.2f} MiB — refusing "
                "admission (shrink the model, quantize weights, or "
                "lower max_batch)")
        free = budget - self.ledger.used()
        if free >= needed:
            return []
        from ..workflow.optimizer.auto_cache import greedy_select

        now = time.perf_counter()
        evictable = {n: e for n, e in self._models.items() if e.ready}
        pinned_bytes = sum(self.ledger.charge_of(n)
                           for n in self._models if n not in evictable)

        def candidates(selected, space_left):
            return [n for n in evictable if n not in selected
                    and self.ledger.charge_of(n) < space_left]

        keep = greedy_select(
            (), candidates,
            lambda n: self.ledger.charge_of(n),
            lambda sel: -sum(evictable[n].retention_value(now)
                             for n in sel),
            budget - needed - pinned_bytes)
        victims = [n for n in evictable if n not in keep]
        kept_bytes = pinned_bytes + sum(self.ledger.charge_of(n)
                                        for n in keep)
        if kept_bytes + needed > budget:
            MetricsRegistry.get_or_create().counter(
                "serving.admission_rejected_total").inc()
            mib = 1 << 20
            raise AdmissionError(
                f"cannot make room for {needed / mib:.2f} MiB under the "
                f"{budget / mib:.2f} MiB budget: "
                f"{kept_bytes / mib:.2f} MiB is pinned by warming/"
                "unevictable models")
        return victims

    def _sync_fence(self) -> None:
        """Arm the steady-state fence exactly when no warmup is in
        flight. Called with the plane lock held; writes only the
        (undeclared) fence flag."""
        if not self.steady_fence or self._closed:
            return
        if self._warming == 0 and not self._fence_armed:
            self._observatory().arm_fence("serving:steady-state")
            self._fence_armed = True

    def _publish_locked(self) -> None:
        """Republish derived residency state (lock held): the gauges,
        and the lock-free ``_live`` snapshot — built FRESH and bound in
        one reference flip, never mutated in place (the atomic-
        publication discipline; readers see the old dict or the new
        one, never a half-updated hybrid)."""
        self._live = {n: e for n, e in self._models.items() if e.ready}
        reg = MetricsRegistry.get_or_create()
        reg.gauge("serving.models_resident").set(len(self._live))
        reg.gauge("serving.models_warming").set(self._warming)

    # -- warmup ------------------------------------------------------------
    def _warm(self, entry: ServedModel) -> None:
        """Compile every steady-state program for this model: each
        bucket at FULL fill (the unmasked program) and at partial fill
        (the mask program — ``n < padded_n`` routes through
        ``_zero_masked_rows``), plus the drift-sketch program when a
        baseline rides the model. Runs with the fence disarmed; the
        numerics gauges stay untouched (a zeros warmup batch is not
        traffic)."""
        for bucket in entry.buckets:
            # mid-warmup fault site: a fault between buckets must roll
            # the whole admission back (_finish_warmup ok=False) — no
            # half-warmed model is ever published
            inject("serve.admit", context=(entry.name, bucket))
            self._execute(entry, _zeros_batch(entry.sample, bucket), bucket)
            if bucket > 1:
                partial = bucket - 1
                self._execute(
                    entry, _zeros_batch(entry.sample, partial), partial)
        if entry.baseline is not None:
            from ..observability.numerics import numerics_suppressed

            # the sketch program compiles per (bucket, d) shape like
            # the apply programs: warm it for EVERY bucket, or the
            # first drift score on a larger bucket would compile under
            # the armed steady-state fence (review finding)
            for bucket in entry.buckets:
                ds = self._bucketed(
                    entry, _zeros_batch(entry.sample, bucket), bucket)
                try:
                    with numerics_suppressed():
                        self._score_drift(entry, ds)
                except ValueError:
                    self._disable_drift(entry)
                    break

    # -- request path ------------------------------------------------------
    @hotpath
    def submit(self, name: str, x: Any,
               timeout_s: Optional[float] = None,
               deadline_ms: Optional[float] = None):
        """Enqueue one request; returns a Future resolving to the model
        output for exactly the submitted rows (pad stripped). ``x`` is
        one item (the admitted sample shape) or a leading-dim batch of
        them, up to the largest bucket. ``deadline_ms`` (relative to
        enqueue) sheds the request BEFORE dispatch if it is still
        queued past the budget — the future then raises
        :class:`~.batcher.DeadlineExpiredError`."""
        return self.submit_request(name, x, timeout_s=timeout_s,
                                   deadline_ms=deadline_ms).future

    @hotpath
    def submit_request(self, name: str, x: Any,
                       timeout_s: Optional[float] = None,
                       deadline_ms: Optional[float] = None) -> Request:
        """:meth:`submit`, returning the whole
        :class:`~.batcher.Request` — ``request.trace`` carries the
        request-path span record (trace id, phase stamps)."""
        # lock-free fast path over the published ready snapshot: the
        # steady-state request pays no plane-lock acquire (and never
        # queues behind an admission holding it); misses fall to the
        # locked slow path for the accurate warming-vs-unknown verdict
        entry = self._live.get(name)
        if entry is None:
            with self._lock:
                entry = self._models.get(name)
                if entry is None:
                    known = sorted(self._models) + [
                        f"{k} (evicted)" for k in sorted(self._evicted)]
                    raise ModelNotAdmitted(
                        f"model {name!r} is not resident "
                        f"(known: {known or 'none'})")
                if not entry.ready:
                    raise ModelWarming(
                        f"model {name!r} is still warming")
        x_tree, n = self._normalize(name, entry.sample, x)
        return self.batcher.submit_request(name, x_tree, n,
                                           timeout_s=timeout_s,
                                           deadline_ms=deadline_ms)

    @hotpath
    def predict(self, name: str, x: Any, timeout_s: float = 60.0,
                deadline_ms: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        return self.submit(name, x, deadline_ms=deadline_ms).result(
            timeout=timeout_s)

    @hotpath
    def predict_traced(self, name: str, x: Any, timeout_s: float = 60.0,
                       deadline_ms: Optional[float] = None):
        """:meth:`predict`, returning ``(output, trace_id)`` —
        ``trace_id`` is ``""`` when tracing is suppressed/disabled.
        The HTTP handler serves this as the ``X-Keystone-Trace``
        response header."""
        req = self.submit_request(name, x, deadline_ms=deadline_ms)
        out = req.future.result(timeout=timeout_s)
        return out, ("" if req.trace is None else req.trace.trace_id)

    def _normalize(self, name: str, sample: Any,
                   x: Any) -> Tuple[Any, int]:
        structs = jax.tree_util.tree_leaves(
            sample,
            is_leaf=lambda s: isinstance(s, jax.ShapeDtypeStruct))
        leaves = jax.tree_util.tree_leaves(x)
        if len(leaves) != len(structs):
            raise ValueError(
                f"request for {name!r} has {len(leaves)} leaves, the "
                f"admitted sample has {len(structs)}")
        ns = set()
        out_leaves = []
        for leaf, struct in zip(leaves, structs):
            arr = np.asarray(leaf, dtype=struct.dtype)
            item = tuple(struct.shape)
            if arr.shape == item:
                arr = arr[None]
            elif arr.shape[1:] != item:
                raise ValueError(
                    f"request leaf shape {arr.shape} matches neither "
                    f"item {item} nor (n, *item) for model {name!r}")
            ns.add(arr.shape[0])
            out_leaves.append(arr)
        if len(ns) != 1:
            raise ValueError(
                f"request leaves disagree on row count: {sorted(ns)}")
        n = ns.pop()
        if n > self.policy.max_rows(self._shards):
            raise ValueError(
                f"request of {n} rows exceeds the largest bucket "
                f"({self.policy.max_rows(self._shards)}) — split it")
        rebuilt = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(
                sample,
                is_leaf=lambda s: isinstance(s, jax.ShapeDtypeStruct)),
            out_leaves)
        return rebuilt, int(n)

    @staticmethod
    def _as_sample_struct(sample: Any) -> Any:
        if isinstance(sample, jax.ShapeDtypeStruct):
            return sample
        if (isinstance(sample, tuple) and len(sample) == 2
                and isinstance(sample[0], (tuple, list))):
            return jax.ShapeDtypeStruct(tuple(sample[0]),
                                        np.dtype(sample[1]))
        if hasattr(sample, "shape") and hasattr(sample, "dtype"):
            return jax.ShapeDtypeStruct(tuple(sample.shape), sample.dtype)
        leaves = jax.tree_util.tree_leaves(sample)
        if leaves and all(isinstance(l, jax.ShapeDtypeStruct)
                          for l in leaves):
            return sample
        raise TypeError(
            "sample must describe ONE request item: a "
            "jax.ShapeDtypeStruct (pytree), (shape, dtype), or array")

    # -- execution ---------------------------------------------------------
    def _bucketed(self, entry: ServedModel, x_tree: Any, n: int):
        bucket = self.policy.bucket_for(max(n, 1), self._shards)
        return bucketed_dataset(x_tree, n, bucket, self.mesh)

    @hotpath
    def _execute(self, entry: ServedModel, x_tree: Any, n: int):
        """One padded-bucket apply; returns ``(outputs, ds)`` where
        outputs carries exactly ``n`` rows (pad stripped)."""
        ds = self._bucketed(entry, x_tree, n)
        return self._collect(entry, ds, n), ds

    def _collect(self, entry: ServedModel, ds: Any, n: int):
        """The device half of :meth:`_execute`: dispatch the warm
        program over an already-bucketed dataset and block until the
        host holds the result — the ``dispatch`` phase of the request
        trace is exactly this call."""
        out = entry.fitted.apply(ds).get()
        if isinstance(out, ArrayDataset):
            return out.numpy()
        if isinstance(out, Dataset):
            return out.collect()[:n]
        return np.asarray(out)

    def _score_drift(self, entry: ServedModel, ds) -> None:
        from ..observability.numerics import score_drift

        score_drift(entry.baseline, ds)

    def _disable_drift(self, entry: ServedModel) -> None:
        entry.drift_disabled = True
        entry.baseline = None
        from ..observability.numerics import record_numerics_event

        record_numerics_event(
            "drift_unscorable", model=entry.name,
            reason="request space is not the sketched feature space "
                   "(baseline rides an upstream stage)")

    def _phase_instruments(self, name: str) -> Dict[str, Tuple[Any, Any]]:
        """``phase -> (aggregate, per-model)`` histogram pairs for one
        model, resolved on first use and cached for the worker's hot
        loop. Entries leave the cache when their model leaves the plane
        (evict / admission victims / warmup rollback). Invalidated
        wholesale when the metrics registry instance changes (test
        harnesses reset it between cases)."""
        reg = MetricsRegistry.get_or_create()
        if reg is not self._phase_reg:
            self._phase_reg = reg
            self._phase_hists = {}
        pairs = self._phase_hists.get(name)
        if pairs is None:
            pairs = {ph: (reg.histogram(f"serving.phase_ms.{ph}"),
                          reg.histogram(f"serving.phase_ms.{ph}.{name}"))
                     for ph in PHASES}
            self._phase_hists[name] = pairs
        return pairs

    # -- the worker --------------------------------------------------------
    def _worker_loop(self, primary: bool = True) -> None:
        t = threading.current_thread()
        if primary:
            # only the primary worker owns the cached span identity;
            # extra workers (KEYSTONE_SERVE_WORKERS > 1) resolve theirs
            # per batch in _record_batch_trace
            self._worker_tid = t.ident or 0
            self._worker_name = t.name
        max_rows = self.policy.max_rows(self._shards)
        while not self._stop.is_set():
            batch = self.batcher.take(max_rows, timeout_s=0.05)
            if batch:
                self._serve_batch(batch)
            else:
                # idle moment: materialize this worker's deferred
                # telemetry (spans + phase observes) off the hot path
                flight_recorder().flush()

    @hotpath
    def _serve_batch(self, requests: List[Request]) -> None:
        taken = len(requests)
        reg = MetricsRegistry.get_or_create()
        try:
            requests = self._shed_expired(requests, reg)
            if not requests:
                # every member expired while queued: zero device work
                # for the whole batch (the finally still frees slots)
                return
            name = requests[0].model
            with self._lock:
                entry = self._models.get(name)
            if entry is None or not entry.ready:
                raise ModelNotAdmitted(
                    f"model {name!r} was evicted while queued")
            rows = sum(r.n for r in requests)
            t_merge = time.perf_counter()  # coalesce/pad phase starts
            merged = jax.tree_util.tree_map(
                lambda *leaves: np.concatenate(leaves, axis=0),
                *[r.x for r in requests])
            # value-carrying fault site: a kind="corrupt" rule poisons
            # the merged batch exactly where a bad client payload or a
            # host-memory flip would land — upstream of the device
            merged = corrupt("serve.dispatch", merged, context=name)
            ds = self._bucketed(entry, merged, rows)
            # abort= lets a "hang" injection end at shutdown: without
            # it, close() burns its whole join timeout waiting out a
            # hung dispatch (the bug the straggler scenario caught)
            inject("serve.dispatch", context=name,
                   abort=self._stop.is_set)
            t0 = time.perf_counter()       # device dispatch starts
            outputs = self._collect(entry, ds, rows)
            t_done = time.perf_counter()   # block_until_ready returned
            if self.nonfinite_guard:
                bad = _count_nonfinite(outputs)
                if bad:
                    raise PoisonedBatchError(
                        f"batch for {name!r} produced {bad} non-finite "
                        f"output value(s) over {rows} rows — failing "
                        "this batch's requests; the worker survives")
            batch_ms = (t_done - t0) * 1e3
            bucket = ds.padded_n
            fill = rows / float(bucket)
            offset = 0
            for req in requests:
                out_i = self._slice_rows(outputs, offset, req.n)
                offset += req.n
                tr = req.trace
                if tr is not None:
                    # every stamp lands BEFORE the future resolves, so
                    # a trace the submitter can observe is immutable
                    tr.dispatch_s = t0
                    tr.done_s = t_done
                    tr.bucket = bucket
                    tr.fill = fill
                    tr.responded_s = time.perf_counter()
                req.future.set_result(out_i)
            now = time.perf_counter()
            reg.counter("serving.requests_total").inc(len(requests))
            reg.counter("serving.rows_total").inc(rows)
            reg.counter("serving.batches_total").inc()
            reg.histogram("serving.batch_ms").observe(batch_ms)
            reg.histogram("serving.batch_fill").observe(fill)
            reg.histogram(f"serving.batch_fill.{name}").observe(fill)
            traced = []
            for req in requests:
                tr = req.trace
                if tr is not None and tr.complete():
                    traced.append(tr)
                    wait_ms = tr.request_ms()
                else:
                    wait_ms = (now - req.enqueued_s) * 1e3
                reg.histogram("serving.request_ms").observe(wait_ms)
                reg.histogram(
                    f"serving.request_ms.{name}").observe(wait_ms)
                # queued time in SECONDS (enqueue -> coalesce start):
                # the one measured congestion signal the router's spill
                # eligibility and the bench fleet line both read — a
                # replica with a deep queue_wait tail is not eligible
                # to absorb spilled traffic (satellite: queue-wait)
                qwait_s = max(t_merge - req.enqueued_s, 0.0)
                reg.histogram("serving.queue_wait_s").observe(qwait_s)
                reg.histogram(
                    f"serving.queue_wait_s.{name}").observe(qwait_s)
                self.slo.record(name, wait_ms)
            if traced:
                self._record_batch_trace(name, traced, t_merge,
                                         bucket, fill)
            with self._lock:
                entry.note_served(rows, len(requests), now)
                score_now = (not entry.drift_disabled
                             and entry.baseline is not None
                             and entry.batches % self.drift_every == 0)
            if score_now:
                # scored AFTER futures resolved: drift work never adds
                # request latency, so it is a batch-level phase outside
                # the per-request telescoping sum (pinned test)
                t_drift = time.perf_counter()
                try:
                    self._score_drift(entry, ds)
                except ValueError:
                    self._disable_drift(entry)
                reg.histogram("serving.phase_ms.drift_score").observe(
                    (time.perf_counter() - t_drift) * 1e3)
        except BaseException as exc:
            self._fail_batch(requests, exc, reg)
        finally:
            self.batcher.done(taken)

    def _shed_expired(self, requests: List[Request],
                      reg: MetricsRegistry) -> List[Request]:
        """Fail every deadline-expired member BEFORE dispatch (504-
        shaped :class:`~.batcher.DeadlineExpiredError`) and return the
        still-live remainder. An expired request burns zero device
        time: it never reaches ``_bucketed``/``_collect``. One clock
        read decides for the whole batch, so a batch is split exactly
        once (no member can expire 'between' shed and the verdict)."""
        now = time.perf_counter()
        live = [r for r in requests if not r.expired(now)]
        if len(live) == len(requests):
            return live
        shed = [r for r in requests if r.expired(now)]
        for req in shed:
            if not req.future.done():
                req.future.set_exception(DeadlineExpiredError(
                    f"request for {req.model!r} spent "
                    f"{(now - req.enqueued_s) * 1e3:.1f} ms queued, "
                    "past its deadline — shed before dispatch"))
                self.slo.record(req.model, None, ok=False)
        reg.counter("serving.deadline_expired_total").inc(len(shed))
        reg.counter("serving.shed_total").inc(len(shed))
        return live

    def _fail_batch(self, requests: List[Request], exc: BaseException,
                    reg: MetricsRegistry) -> None:
        """The failed-batch epilogue: classify, attach one (throttled)
        post-mortem, fail exactly the still-unresolved futures, and
        record ONE SLO outcome per request failed HERE — a request
        whose future already resolved (or was shed) was already
        recorded, and re-recording it skews the availability window
        (the double-count the chaos suite caught). Routing verdicts
        (not-admitted / warming) stay classification-only: they carry
        no post-mortem. Cold by design (HOTPATH_COLD): runs once per
        failed batch, never on the request fast path."""
        name = requests[0].model
        reg.counter("serving.errors_total").inc()
        if isinstance(exc, PoisonedBatchError):
            reg.counter("serving.poisoned_batches_total").inc()
        if not isinstance(exc, (ModelNotAdmitted, ModelWarming)):
            now = time.perf_counter()
            if (now - self._last_batch_pm_s
                    >= self.postmortem_min_interval_s):
                self._last_batch_pm_s = now
                from ..observability.postmortem import attach_postmortem

                attach_postmortem(exc, "serving_batch_failure", context={
                    "model": name,
                    "requests": len(requests),
                    "rows": sum(r.n for r in requests),
                    "error": f"{type(exc).__name__}: {exc}",
                })
        for req in requests:
            if not req.future.done():
                req.future.set_exception(exc)
                self.slo.record(name, None, ok=False)

    def _record_batch_trace(self, name: str, traces: List[Any],
                            start_s: float, bucket: int,
                            fill: float) -> None:
        """One ``request:`` span per completed member trace plus the
        ``batch:`` span they rode, linked by Chrome-trace flow ids
        (``flow_out`` on each request span, the matching ``flow_in``
        list on the batch span — ``timeline.to_chrome_trace`` exports
        them as ``ph:"s"``/``ph:"f"`` flow events, so Perfetto draws a
        request's causal path through the coalesced batch). Completed
        traces also feed the slowest-N exemplar reservoir. Hot path —
        runs between a batch's futures resolving and the worker's next
        ``take``, so EVERYTHING here is DEFERRED via
        ``FlightRecorder.defer`` (span construction — f-strings, args
        dicts, the ring lock — the phase-histogram observes, AND the
        reservoir offers) and materialized at the next flush point
        (any recorder view, the HTTP scrape surface, the idle worker,
        and — because the offers ride along — the SLO escalation path,
        which flushes before reading exemplars). Completed traces are
        immutable, so late materialization reads exactly what the
        worker stamped; the inline cost is one mint, one tuple, and
        one deque append."""
        rec = flight_recorder()
        batch_id = mint_flow_id()
        if self.workers > 1:
            # concurrent dispatch: the span must land on the lane of
            # the thread that actually served this batch
            wt = threading.current_thread()
            tid, thread = wt.ident or 0, wt.name
        else:
            tid, thread = self._worker_tid, self._worker_name
        if rec.enabled:
            members = tuple(traces)
            rec.defer(lambda: self._materialize_batch_telemetry(
                rec, name, members, start_s, bucket, fill, batch_id,
                tid, thread))
        else:
            # no recorder, no flush point: the scrape surface still
            # owes the phase histograms and the reservoir its
            # exemplars, so both run inline
            reservoir = exemplar_reservoir()
            for tr in traces:
                tr.batch_id = batch_id
                reservoir.offer(tr)
            self._observe_phases(name, traces)

    def _observe_phases(self, name: str, traces: Any) -> None:
        """Feed the ``serving.phase_ms.<phase>[.<model>]`` histogram
        pairs one decomposition per completed trace."""
        pairs = self._phase_instruments(name)
        for tr in traces:
            for phase, ms in tr.phases_ms().items():
                agg, per_model = pairs[phase]
                agg.observe(ms)
                per_model.observe(ms)

    def _materialize_batch_telemetry(self, rec: Any, name: str,
                                     traces: tuple, start_s: float,
                                     bucket: int, fill: float,
                                     batch_id: int, tid: int,
                                     thread: str) -> None:
        """The deferred half of :meth:`_record_batch_trace`: feeds the
        exemplar reservoir, builds the ``request:``/``batch:`` spans,
        and runs the phase-histogram observes when the recorder is
        flushed. ``tid``/``thread`` are the worker identity captured
        at defer time, so the spans land on the worker's lane."""
        reservoir = exemplar_reservoir()
        for tr in traces:
            tr.batch_id = batch_id
            reservoir.offer(tr)
        self._observe_phases(name, traces)
        end_s = start_s
        req_span = "request:" + name
        for tr in traces:
            if tr.responded_s > end_s:
                end_s = tr.responded_s
            rec.record(req_span, "serving", tr.enqueued_s,
                       tr.responded_s - tr.enqueued_s,
                       args={"trace_id": tr.trace_id, "n": tr.n,
                             "batch": batch_id, "flow_out": tr.flow_id,
                             "phases_ms": tr.phases_ms()},
                       tid=tid, thread=thread)
        rec.record("batch:" + name, "serving", start_s, end_s - start_s,
                   args={"batch": batch_id, "bucket": bucket,
                         "fill": round(fill, 4), "requests": len(traces),
                         "flow_in": [tr.flow_id for tr in traces]},
                   tid=tid, thread=thread)

    @staticmethod
    def _slice_rows(outputs: Any, offset: int, n: int) -> Any:
        if isinstance(outputs, list):  # host collect() output
            return outputs[offset:offset + n]
        return jax.tree_util.tree_map(
            lambda leaf: leaf[offset:offset + n], outputs)

    # -- introspection -----------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """JSON-able plane state (the ``/models`` endpoint body). The
        readiness verdict, model list, warming count, and evicted set
        come from ONE lock hold: a poll racing admit/evict churn sees
        a coherent instant (ready=True with a half-warmed model list
        was the bug — the verdict and the list it judged were read at
        different times)."""
        with self._lock:
            ready = self._ready_locked()
            models = [e.state() for e in self._models.values()]
            evicted = sorted(self._evicted)
            warming = self._warming
        return {
            "ready": ready,
            "warming": warming,
            "hbm_budget_bytes": self.ledger.budget,
            "hbm_charged_bytes": self.ledger.used(),
            "buckets": list(self.policy.rows(self._shards)),
            "queue_depth": self.batcher.depth(),
            "models": sorted(models, key=lambda m: m["name"]),
            "evicted": evicted,
        }

    def unexpected_recompiles(self) -> float:
        """The ``compile.unexpected_total`` counter — with the
        steady-state fence armed, any nonzero delta across a serving
        window is a recompile bug, not noise."""
        return MetricsRegistry.get_or_create().counter(
            "compile.unexpected_total").value
