"""The fleet front door: consistent-hash routing with honest spill.

One thin stdlib HTTP router fronts N serving replicas and speaks the
SAME surface a single replica does (``POST /predict/<model>``,
``GET /healthz``, ``GET /models``, ``GET /metrics``), so a client
cannot tell one replica from a fleet — except that the fleet keeps
answering when a replica dies:

* **Routing** is rendezvous (highest-random-weight) hashing by model
  name over the replicas hosting that model: stable under membership
  change (a dead replica re-routes ONLY its own models — no global
  reshuffle), deterministic, and coordination-free.
* **Spill**: when the primary's queue is deep (the measured congestion
  the per-model ``serving.queue_wait_s`` histogram exists to expose)
  or the primary refuses (429/503/connection refused), the request
  spills to the least-loaded eligible replica hosting the model —
  counted per model (``router.spill_total.<model>``), because a rising
  spill share is the "scale out" signal BEFORE p99 moves
  (PERFORMANCE.md rule 19).
* **Honest refusal**: when nobody eligible hosts the model the router
  answers 503 with ``Retry-After`` — a classified verdict, never an
  unclassified error; a fleet mid-recovery degrades loudly.

Two replica transports implement one client surface
(:class:`LocalReplicaClient` wraps an in-process plane — the bench
path, where JSON framing would swamp the measurement;
:class:`HttpReplicaClient` speaks real HTTP to a replica process — the
CI fleet gate and chaos path), and two router surfaces share one
routing core (:meth:`FleetRouter.submit_request` duck-types the plane
surface so the loadgen replays through the router unchanged;
:class:`RouterHandler` forwards raw HTTP bytes, preserving the
replica's own classified statuses and headers verbatim).

``_table`` (model -> replica clients) follows the plane's published-
snapshot discipline: rebuilt fresh and rebound in one reference flip
under the router lock, read lock-free on the request path (the
``analysis/hotpath.py`` publication pass checks it).
"""
from __future__ import annotations

import hashlib
import http.client
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability.metrics import MetricsRegistry
from ..utils.guarded import (TracedLock, guarded_by, hotpath,
                             published_by)
from .batcher import QueueFullError, Request
from .http import _JsonReplyHandler, _err, bind_server, predict_response
from .plane import ModelNotAdmitted, ModelWarming, ServingPlane


def _rendezvous_score(model: str, replica_id: str) -> int:
    """Highest-random-weight score: stable across processes and runs
    (sha256, not the salted builtin hash), so every router instance
    agrees on the primary without coordinating."""
    digest = hashlib.sha256(
        f"{model}|{replica_id}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class LocalReplicaClient:
    """An in-process replica: direct plane calls, zero serialization.
    The bench transport — measuring fleet scale-out must not measure
    JSON framing — and the unit-test double for the HTTP client."""

    def __init__(self, replica_id: str, plane: ServingPlane):
        self.replica_id = replica_id
        self.plane = plane

    @hotpath
    def submit_request(self, name: str, x: Any,
                       timeout_s: Optional[float] = None,
                       deadline_ms: Optional[float] = None) -> Request:
        return self.plane.submit_request(name, x, timeout_s=timeout_s,
                                         deadline_ms=deadline_ms)

    @hotpath
    def predict_raw(self, name: str, raw: bytes
                    ) -> Tuple[int, bytes, Optional[Dict[str, str]]]:
        return predict_response(self.plane, name, raw)

    def queue_depth(self) -> int:
        return self.plane.batcher.depth()

    def models(self) -> Tuple[str, ...]:
        # the published lock-free snapshot IS the ready-model list
        return tuple(sorted(self.plane._live))

    def model_shas(self) -> Dict[str, str]:
        return {name: hashlib.sha256(entry.blob).hexdigest()
                for name, entry in sorted(self.plane._live.items())}

    def admit_blob(self, name: str, blob: bytes, sample: Any,
                   weight_dtype: Optional[str]) -> str:
        """Admit from canonical bytes; returns the sha256 of the
        replica's OWN canonical blob — the migration bit-identity
        verdict is the caller comparing it against the source's."""
        import pickle

        entry = self.plane.admit(name, pickle.loads(blob), sample,
                                 weight_dtype=weight_dtype)
        return hashlib.sha256(entry.blob).hexdigest()

    def evict(self, name: str) -> None:
        self.plane.evict(name)

    def probe(self) -> str:
        """``"ready"`` / ``"warming"`` / ``"dead"`` — the controller's
        health verdict."""
        if getattr(self.plane, "_closed", False):
            return "dead"
        return "ready" if self.plane.ready() else "warming"


class HttpReplicaClient:
    """A replica process over real HTTP — same surface as the local
    client, every call a fresh bounded-timeout connection
    (``http.client`` connections are not thread-safe; the router's
    handler threads must not share one). Connection failures surface
    as ``ConnectionError`` so the router's spill/refusal path and the
    loadgen classifier both see one exception family."""

    def __init__(self, replica_id: str, host: str, port: int,
                 timeout_s: float = 10.0, stats_ttl_s: float = 0.25):
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        #: /models is scraped at most once per TTL for routing stats —
        #: a per-request scrape would double every request's HTTP cost
        self.stats_ttl_s = float(stats_ttl_s)
        self._stats: Tuple[float, Dict[str, Any]] = (-1e18, {})

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            try:
                conn.request(method, path, body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
                headers = {k: v for k, v in resp.getheaders()}
                return resp.status, payload, headers
            except (OSError, http.client.HTTPException) as exc:
                raise ConnectionError(
                    f"replica {self.replica_id} at "
                    f"{self.host}:{self.port}: {exc}") from exc
        finally:
            conn.close()

    @hotpath
    def predict_raw(self, name: str, raw: bytes
                    ) -> Tuple[int, bytes, Optional[Dict[str, str]]]:
        status, body, headers = self._request(
            "POST", f"/predict/{name}", body=raw)
        keep = {k: v for k, v in headers.items()
                if k.lower() in ("retry-after", "x-keystone-trace")}
        return status, body, keep or None

    def _state(self, fresh: bool = False) -> Dict[str, Any]:
        now = time.monotonic()
        stamp, cached = self._stats
        if not fresh and now - stamp < self.stats_ttl_s:
            return cached
        status, body, _ = self._request("GET", "/models")
        state = json.loads(body) if status == 200 else {}
        self._stats = (now, state)
        return state

    def queue_depth(self) -> int:
        return int(self._state().get("queue_depth", 0))

    def models(self) -> Tuple[str, ...]:
        # table rebuilds are rare and correctness-critical: a cached
        # snapshot taken moments before an admission completed would
        # leave the new copy invisible until the next rebuild — bypass
        # the TTL (queue_depth, polled per-request, keeps it)
        return tuple(sorted(
            m["name"] for m in self._state(fresh=True).get("models", ())
            if m.get("ready")))

    def model_shas(self) -> Dict[str, str]:
        status, body, _ = self._request("GET", "/admin/models")
        if status != 200:
            raise ConnectionError(
                f"replica {self.replica_id}: /admin/models -> {status}")
        return dict(json.loads(body))

    def admit_blob(self, name: str, blob: bytes, sample: Any,
                   weight_dtype: Optional[str]) -> str:
        import base64

        from .replica import encode_sample_spec

        payload = json.dumps({
            "name": name,
            "blob_b64": base64.b64encode(blob).decode(),
            "sample": encode_sample_spec(sample),
            "weight_dtype": weight_dtype,
        }).encode()
        status, body, _ = self._request("POST", "/admin/admit",
                                        body=payload)
        if status != 200:
            raise RuntimeError(
                f"replica {self.replica_id}: admit {name!r} -> "
                f"{status}: {body[:200].decode(errors='replace')}")
        return json.loads(body)["sha256"]

    def evict(self, name: str) -> None:
        payload = json.dumps({"name": name}).encode()
        status, body, _ = self._request("POST", "/admin/evict",
                                        body=payload)
        if status != 200:
            raise RuntimeError(
                f"replica {self.replica_id}: evict {name!r} -> "
                f"{status}: {body[:200].decode(errors='replace')}")

    def probe(self) -> str:
        try:
            status, _, _ = self._request("GET", "/healthz")
        except ConnectionError:
            return "dead"
        return "ready" if status == 200 else "warming"


@published_by("_lock", "_table")
@guarded_by("_lock", "_clients")
class FleetRouter:
    """The routing core both surfaces share; see module docstring.

    ``spill_queue_depth`` is the proactive-spill threshold: a primary
    with at least this many queued requests loses the request to the
    least-loaded eligible sibling BEFORE refusing (tune it against the
    ``serving.queue_wait_s`` histogram — depth is the cause,
    queue-wait the symptom the SLO sees)."""

    def __init__(self, clients: Sequence[Any] = (),
                 spill_queue_depth: int = 48):
        self.spill_queue_depth = int(spill_queue_depth)
        self._lock = TracedLock("serving.router")
        self._clients: Dict[str, Any] = {}
        #: published model -> (client, ...) snapshot; rebuilt fresh and
        #: rebound whole under the lock, read lock-free per request
        self._table: Dict[str, Tuple[Any, ...]] = {}
        for client in clients:
            self._clients[client.replica_id] = client
        self.refresh()

    # -- membership ---------------------------------------------------------
    def replica_ids(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._clients))

    def client(self, replica_id: str) -> Any:
        with self._lock:
            return self._clients[replica_id]

    def add_replica(self, client: Any) -> None:
        with self._lock:
            self._clients[client.replica_id] = client
        self.refresh()

    def remove_replica(self, replica_id: str) -> None:
        """Drop a replica (death or drain-complete) and republish the
        table — its models re-route on the next request."""
        with self._lock:
            self._clients.pop(replica_id, None)
        self.refresh()

    def refresh(self) -> None:
        """Rebuild the model->replicas table from what each replica
        actually hosts RIGHT NOW (a replica that cannot answer is left
        out — dead replicas serve nothing). One atomic rebind."""
        with self._lock:
            clients = dict(self._clients)
        table: Dict[str, List[Any]] = {}
        live = 0
        for rid in sorted(clients):
            client = clients[rid]
            try:
                names = client.models()
            except (ConnectionError, OSError):
                continue
            live += 1
            for name in names:
                table.setdefault(name, []).append(client)
        frozen = {n: tuple(cs) for n, cs in table.items()}
        with self._lock:
            self._table = frozen
        reg = MetricsRegistry.get_or_create()
        reg.gauge("router.replicas_live").set(live)
        reg.gauge("fleet.models_placed").set(
            sum(len(cs) for cs in frozen.values()))

    # -- routing core -------------------------------------------------------
    @hotpath
    def _route(self, name: str) -> Tuple[List[Any], Any]:
        """Candidate replicas for ``name`` in try-order, plus the
        rendezvous primary (for spill accounting). Lock-free read of
        the published table."""
        clients = self._table.get(name)
        if not clients:
            known = sorted(self._table)
            raise ModelNotAdmitted(
                f"model {name!r} is on no live replica "
                f"(fleet hosts: {known or 'none'})")
        primary = max(clients,
                      key=lambda c: _rendezvous_score(name,
                                                      c.replica_id))
        if len(clients) == 1:
            return [primary], primary

        def depth_of(client: Any) -> int:
            # a replica that cannot answer its stats probe sorts LAST
            # (effectively infinite depth) — the submit attempt will
            # classify it properly; the probe must never crash routing
            try:
                return client.queue_depth()
            except (ConnectionError, OSError):
                return 1 << 30

        rest = sorted((c for c in clients if c is not primary),
                      key=lambda c: (depth_of(c), c.replica_id))
        order = [primary] + rest
        depth = depth_of(primary)
        if depth >= self.spill_queue_depth \
                and depth_of(rest[0]) < depth:
            # proactive spill: the primary is congested and a sibling
            # is measurably shallower — don't wait for the 429
            order = [rest[0], primary] + rest[1:]
        return order, primary

    @hotpath
    def submit_request(self, name: str, x: Any,
                       timeout_s: Optional[float] = None,
                       deadline_ms: Optional[float] = None) -> Request:
        """The plane-shaped surface (duck-typed by the loadgen): route,
        submit to the first willing replica, spill on refusal. Raises
        the LAST replica's classified refusal when nobody accepts —
        the fleet never converts a classified verdict into mush."""
        reg = MetricsRegistry.get_or_create()
        reg.counter("router.requests_total").inc()
        order, primary = self._route(name)
        last: Optional[BaseException] = None
        for client in order:
            try:
                req = client.submit_request(name, x,
                                            timeout_s=timeout_s,
                                            deadline_ms=deadline_ms)
            except (QueueFullError, ModelWarming, ModelNotAdmitted,
                    ConnectionError) as exc:
                # ModelNotAdmitted from a TABLED replica means the
                # table is stale (mid-migration evict): spill, don't
                # trust the snapshot over the replica's own verdict
                last = exc
                continue
            if client is not primary:
                reg.counter("router.spill_total").inc()
                reg.counter(f"router.spill_total.{name}").inc()
            return req
        reg.counter("router.unavailable_total").inc()
        if isinstance(last, (QueueFullError, ModelWarming,
                             ModelNotAdmitted)):
            raise last
        raise QueueFullError(
            f"no eligible replica for {name!r} "
            f"({len(order)} tried, all unreachable)",
            retry_after_s=1.0)

    @hotpath
    def predict_raw(self, name: str, raw: bytes
                    ) -> Tuple[int, bytes, Optional[Dict[str, str]]]:
        """The HTTP-forwarding surface: same routing/spill decisions,
        verdict carried as raw status/body/headers (the replica's own
        classification passes through verbatim; only an all-replicas-
        refused outcome is the router's to classify — 503 with
        Retry-After)."""
        reg = MetricsRegistry.get_or_create()
        reg.counter("router.requests_total").inc()
        try:
            order, primary = self._route(name)
        except ModelNotAdmitted as exc:
            reg.counter("router.unavailable_total").inc()
            return 404, _err(exc), None
        last: Optional[Tuple[int, bytes, Optional[Dict[str, str]]]] = None
        for client in order:
            try:
                status, body, headers = client.predict_raw(name, raw)
            except ConnectionError as exc:
                last = (503, _err(exc), None)
                continue
            if status in (404, 429, 503):
                # 404 from a TABLED replica = stale table (the model
                # just migrated off it): spill like any refusal
                last = (status, body, headers)
                continue
            if client is not primary:
                reg.counter("router.spill_total").inc()
                reg.counter(f"router.spill_total.{name}").inc()
            return status, body, headers
        reg.counter("router.unavailable_total").inc()
        status, body, headers = last if last is not None else (
            503, _err(QueueFullError(
                f"no eligible replica for {name!r}")), None)
        headers = dict(headers or {})
        if status in (429, 503):
            # every fleet refusal answers WHEN: a 429/503 without
            # Retry-After is an unclassified shrug (the CI gate checks)
            headers.setdefault("Retry-After", "1")
        return status, body, headers

    def ready(self) -> bool:
        """The router's readiness: it can route SOMETHING (at least one
        model on at least one live replica)."""
        if not self._table:
            raise RuntimeError("router has no routable models")
        return True

    def state(self) -> Dict[str, Any]:
        """JSON-able fleet routing state (the router's ``/models``)."""
        table = self._table
        return {
            "replicas": list(self.replica_ids()),
            "models": {
                name: [c.replica_id for c in clients]
                for name, clients in sorted(table.items())},
            "spill_queue_depth": self.spill_queue_depth,
        }


class RouterHandler(_JsonReplyHandler):
    """The router's HTTP surface: ``POST /predict/<model>`` forwards
    through :meth:`FleetRouter.predict_raw`; ``GET /models`` serves the
    fleet routing table; ``/healthz``/``/metrics`` ride the shared
    metrics handler (readiness = the router can route something)."""

    router: Optional[FleetRouter] = None

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        if self.path.split("?")[0] == "/models":
            self._reply(200,
                        json.dumps(self.router.state()).encode())
            return
        super().do_GET()

    @hotpath
    def do_POST(self):  # noqa: N802 (stdlib handler API)
        path = self.path.split("?")[0]
        if not path.startswith("/predict/"):
            self._reply(404, b'{"error": "unknown endpoint"}\n')
            return
        name = path[len("/predict/"):]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
        except (ValueError, TypeError) as exc:
            self._reply(400, _err(exc))
            return
        status, body, headers = self.router.predict_raw(name, raw)
        self._reply(status, body, "application/json", headers=headers)


def serve_router(router: FleetRouter, port: int = 0,
                 host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None):
    """Bind the fleet front door on ``host:port`` (``port=0`` =
    ephemeral) — same server machinery, thread, and shutdown semantics
    as a single replica's :func:`~.http.serve`."""
    return bind_server(
        RouterHandler,
        {"registry": registry, "router": router,
         "ready_probe": staticmethod(router.ready)},
        port=port, host=host, thread_name="keystone-router-http")
