"""The fleet controller: canonical blobs, verified migration, death
recovery, and the telemetry-driven autoscaling reactor.

The controller is the fleet's ONE owner of model bytes. Every model is
registered once and canonicalized exactly the way a plane's ``admit``
would (pickle round-trip, ``to_pipeline``, weight-dtype application,
pickle again — the same four steps, so a replica admitting the shipped
blob reproduces it bit-identically), stamped with its sha256 and its
static admission charge (``serving/residency.py:model_charge`` — the
identical arithmetic ``check --budget`` charges). Placement is then a
pure solve (:func:`~.placement.plan_placement`) over those demands and
the per-replica HBM budgets, and every fleet mutation is the DIFF
between the live placement and a fresh solve, applied in the one safe
order:

    admit on the target -> VERIFY (replica's sha256 == canonical
    sha256; a mismatch aborts the migration with the model still live
    on the source) -> evict on the source.

Capacity is briefly double-charged during a migration, never
zero-charged, and bytes never take a lossy hop — the canonical-bytes
contract the single plane pins for evict/readmit, extended across
processes.

**Death** is the same machinery: a failed health probe removes the
replica from the router (its models re-route instantly to surviving
copies or 503 honestly), counts ``fleet.replica_deaths_total``, and
triggers a re-solve over the survivors — re-admission of the lost
models from canonical bytes, verified the same way.

**Autoscaling** (:class:`FleetAutoscaler`) is a reactor over measured
serving telemetry, never a guess: sustained queue depth across the
fleet (the cause the per-model ``serving.queue_wait_s`` histogram
prices into latency) scales up through a caller-supplied provisioner;
a sustained idle fleet drains its highest-numbered replica (migrate
off, verify, then retire). Every ``tick()`` is synchronous and
deterministic given its scraped inputs — the chaos scenarios and the
CI fleet gate drive it directly; ``run_reactor`` is the thin
wall-clock thread for production use.
"""
from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from ..observability.metrics import MetricsRegistry
from .models import _apply_weight_dtype
from .placement import ModelDemand, Placement, plan_placement
from .plane import ServingPlane
from .residency import model_charge
from .router import FleetRouter


class FleetError(RuntimeError):
    """A fleet mutation failed loudly (sha mismatch, missing replica,
    refused admission) — the fleet never papers over a failed step."""


@dataclass(frozen=True)
class FleetModel:
    """One registered model: canonical bytes + placement demand."""

    name: str
    blob: bytes
    sample: Any
    weight_dtype: Optional[str]
    sha256: str
    charge_nbytes: float
    qps: float = 0.0
    warmup_s: float = 0.0

    def demand(self) -> ModelDemand:
        return ModelDemand(name=self.name,
                           charge_nbytes=self.charge_nbytes,
                           qps=self.qps, warmup_s=self.warmup_s)


def canonicalize(fitted: Any, sample: Any,
                 weight_dtype: Optional[str],
                 bucket_rows: int = 64) -> Tuple[bytes, float]:
    """Mint the canonical blob + static charge for ``fitted`` — the
    exact byte-production steps of ``ServingPlane.admit``, run once by
    the controller instead of once per replica, so every replica's
    admitted blob can be sha-checked against ONE source of truth."""
    working = pickle.loads(pickle.dumps(fitted))
    pipeline = working.to_pipeline()
    _apply_weight_dtype(pipeline.graph, weight_dtype)
    blob = pickle.dumps(working)
    struct = ServingPlane._as_sample_struct(sample)
    charge = model_charge(pipeline, struct, bucket_rows)
    return blob, charge.total_nbytes()


class FleetController:
    """See module docstring. ``budgets`` maps replica id -> HBM budget
    in bytes (``None`` = unbounded); replicas themselves are the
    router's clients — the controller only ever addresses them through
    the router's membership so the two cannot disagree about who is in
    the fleet."""

    def __init__(self, router: FleetRouter,
                 budgets: Optional[Mapping[str, Optional[float]]] = None,
                 bucket_rows: int = 64):
        self.router = router
        self.bucket_rows = int(bucket_rows)
        self._budgets: Dict[str, Optional[float]] = dict(budgets or {})
        self._models: Dict[str, FleetModel] = {}
        self._placement = Placement()
        # cold-path mutual exclusion (register/rebalance/death); the
        # request path never takes this lock
        self._lock = threading.RLock()

    # -- registration -------------------------------------------------------
    def register(self, name: str, fitted: Any, sample: Any,
                 weight_dtype: Optional[str] = None,
                 qps: float = 0.0, warmup_s: float = 0.0) -> FleetModel:
        """Canonicalize and register a model with the fleet. Placement
        happens on the next :meth:`rebalance` — registration is pure
        bookkeeping."""
        import hashlib

        blob, charge = canonicalize(fitted, sample, weight_dtype,
                                    self.bucket_rows)
        model = FleetModel(
            name=name, blob=blob, sample=sample,
            weight_dtype=weight_dtype,
            sha256=hashlib.sha256(blob).hexdigest(),
            charge_nbytes=charge, qps=float(qps),
            warmup_s=float(warmup_s))
        with self._lock:
            if name in self._models:
                raise ValueError(
                    f"model {name!r} is already registered")
            self._models[name] = model
        return model

    def note_demand(self, name: str, qps: Optional[float] = None,
                    warmup_s: Optional[float] = None) -> None:
        """Fold observed demand (scraped QPS, measured warmup wall)
        into a model's placement inputs — the signal the next
        rebalance replicates hot models with."""
        with self._lock:
            model = self._models[name]
            self._models[name] = replace(
                model,
                qps=model.qps if qps is None else float(qps),
                warmup_s=(model.warmup_s if warmup_s is None
                          else float(warmup_s)))

    def set_budget(self, replica_id: str,
                   budget: Optional[float]) -> None:
        with self._lock:
            self._budgets[replica_id] = budget

    @property
    def placement(self) -> Placement:
        return self._placement

    # -- solve + apply ------------------------------------------------------
    def _live_budgets(self) -> Dict[str, Optional[float]]:
        live = self.router.replica_ids()
        return {rid: self._budgets.get(rid) for rid in live}

    def solve(self) -> Placement:
        """A fresh placement over the LIVE replicas — pure, applies
        nothing."""
        with self._lock:
            demands = [m.demand() for m in self._models.values()]
            return plan_placement(demands, self._live_budgets())

    def rebalance(self) -> List[Tuple[str, str, str]]:
        """Solve, diff against the live placement, apply (admit ->
        verify -> evict), republish the routing table. Returns the
        applied steps. One failed step raises :class:`FleetError` with
        everything already applied left in place — re-running
        ``rebalance`` resumes from the surviving state."""
        with self._lock:
            target = self.solve()
            steps = self._placement.diff(target)
            for kind, name, replica_id in steps:
                if kind == "admit":
                    self._admit_step(name, replica_id)
                else:
                    self._evict_step(name, replica_id)
            self._placement = target
        self.router.refresh()
        if steps:
            MetricsRegistry.get_or_create().counter(
                "router.rebalance_total").inc()
        return steps

    def _admit_step(self, name: str, replica_id: str) -> None:
        model = self._models[name]
        try:
            client = self.router.client(replica_id)
        except KeyError:
            raise FleetError(
                f"admit {name!r}: replica {replica_id!r} is not in "
                "the fleet") from None
        got = client.admit_blob(model.name, model.blob, model.sample,
                                model.weight_dtype)
        if got != model.sha256:
            # the replica holds DIFFERENT bytes than the canon — evict
            # the impostor copy before anything routes to it
            try:
                client.evict(name)
            finally:
                raise FleetError(
                    f"migration of {name!r} to {replica_id!r} is not "
                    f"bit-identical: canonical sha256 {model.sha256} "
                    f"!= admitted {got} — aborted with the source "
                    "copy still live")

    def _evict_step(self, name: str, replica_id: str) -> None:
        try:
            client = self.router.client(replica_id)
        except KeyError:
            return  # the source died mid-migration: nothing to evict
        client.evict(name)

    # -- membership ---------------------------------------------------------
    def add_replica(self, client: Any,
                    budget: Optional[float] = None) -> None:
        """Scale-up: join a replica (fresh and empty) to the fleet and
        rebalance onto it."""
        with self._lock:
            self._budgets[client.replica_id] = budget
        self.router.add_replica(client)
        self.rebalance()

    def drain_replica(self, replica_id: str) -> None:
        """Scale-down, the safe order: re-solve WITHOUT the victim,
        migrate its models off (admit->verify->evict), then retire it.
        The victim serves until its last model leaves."""
        with self._lock:
            budgets = self._live_budgets()
            if replica_id not in budgets:
                raise FleetError(
                    f"drain: replica {replica_id!r} is not live")
            if len(budgets) == 1:
                raise FleetError(
                    "drain refused: cannot retire the last replica")
            del budgets[replica_id]
            demands = [m.demand() for m in self._models.values()]
            target = plan_placement(demands, budgets)
            for kind, name, rid in self._placement.diff(target):
                if kind == "admit":
                    self._admit_step(name, rid)
                else:
                    self._evict_step(name, rid)
            self._placement = target
            self._budgets.pop(replica_id, None)
        self.router.remove_replica(replica_id)
        MetricsRegistry.get_or_create().counter(
            "router.rebalance_total").inc()

    def handle_death(self, replica_id: str) -> List[Tuple[str, str, str]]:
        """A replica stopped answering: remove it, count it, re-solve
        over the survivors, re-admit the lost models from canonical
        bytes (verified — recovery is a migration, not a guess)."""
        MetricsRegistry.get_or_create().counter(
            "fleet.replica_deaths_total").inc()
        self.router.remove_replica(replica_id)
        with self._lock:
            self._budgets.pop(replica_id, None)
            # forget the dead copies so the diff re-admits elsewhere
            # instead of trying to evict from a corpse
            survivors = {
                m: tuple(r for r in reps if r != replica_id)
                for m, reps in self._placement.assignments.items()}
            self._placement = Placement(
                assignments={m: reps for m, reps in survivors.items()
                             if reps},
                loads={r: v for r, v in self._placement.loads.items()
                       if r != replica_id})
        return self.rebalance()

    def probe(self) -> List[str]:
        """Health-check every fleet replica; dead ones go through
        :meth:`handle_death`. Returns the ids that died."""
        dead = []
        for rid in self.router.replica_ids():
            try:
                verdict = self.router.client(rid).probe()
            except KeyError:
                continue
            if verdict == "dead":
                dead.append(rid)
        for rid in dead:
            self.handle_death(rid)
        return dead

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": sorted(self._models),
                "budgets": dict(self._budgets),
                "placement": {m: list(reps) for m, reps in
                              sorted(self._placement.assignments.items())},
            }


class FleetAutoscaler:
    """The reactor: every tick probes for deaths and turns measured
    congestion into membership changes. ``provisioner`` is a zero-arg
    callable returning a fresh (empty) replica client — how a new
    replica comes to exist is the deployment's business (the CI gate
    spawns a subprocess, the bench builds a plane in-process); WHETHER
    one should exist is the reactor's, and it only ever decides from
    scraped telemetry."""

    def __init__(self, controller: FleetController,
                 provisioner: Optional[Callable[[], Any]] = None,
                 replica_budget: Optional[float] = None,
                 scale_up_queue_depth: int = 32,
                 scale_down_queue_depth: int = 2,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 sustain_ticks: int = 3):
        self.controller = controller
        self.provisioner = provisioner
        self.replica_budget = replica_budget
        self.scale_up_queue_depth = int(scale_up_queue_depth)
        self.scale_down_queue_depth = int(scale_down_queue_depth)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = int(max_replicas)
        #: consecutive ticks a signal must hold before acting — one
        #: bursty scrape must not flap the fleet
        self.sustain_ticks = max(int(sustain_ticks), 1)
        self._hot_ticks = 0
        self._idle_ticks = 0

    def _depths(self) -> Dict[str, int]:
        router = self.controller.router
        depths = {}
        for rid in router.replica_ids():
            try:
                depths[rid] = router.client(rid).queue_depth()
            except (KeyError, ConnectionError, OSError):
                continue
        return depths

    def tick(self) -> Optional[str]:
        """One reactor step; returns the action taken (``"death"``,
        ``"scale_up"``, ``"scale_down"``, ``"rebalance"``) or None."""
        if self.controller.probe():
            self._hot_ticks = self._idle_ticks = 0
            return "death"
        depths = self._depths()
        n = len(depths)
        if not depths:
            return None
        mean_depth = sum(depths.values()) / n
        if mean_depth >= self.scale_up_queue_depth:
            self._hot_ticks += 1
            self._idle_ticks = 0
        elif max(depths.values()) <= self.scale_down_queue_depth:
            self._idle_ticks += 1
            self._hot_ticks = 0
        else:
            self._hot_ticks = self._idle_ticks = 0
        if (self._hot_ticks >= self.sustain_ticks
                and self.provisioner is not None
                and n < self.max_replicas):
            self._hot_ticks = 0
            self.controller.add_replica(self.provisioner(),
                                        budget=self.replica_budget)
            return "scale_up"
        if (self._idle_ticks >= self.sustain_ticks
                and n > self.min_replicas):
            self._idle_ticks = 0
            victim = max(self.controller.router.replica_ids())
            self.controller.drain_replica(victim)
            return "scale_down"
        # demand drift without membership change: apply any pending
        # replication the latest note_demand() calls justify
        with self.controller._lock:
            pending = self.controller._placement.diff(
                self.controller.solve())
        if pending:
            self.controller.rebalance()
            return "rebalance"
        return None


def run_reactor(autoscaler: FleetAutoscaler,
                stop: threading.Event,
                interval_s: float = 1.0) -> threading.Thread:
    """The wall-clock wrapper: tick until ``stop`` is set. Daemon
    thread — join it via the returned handle after setting ``stop``."""

    def loop():
        while not stop.wait(interval_s):
            try:
                autoscaler.tick()
            except FleetError:
                # a failed step leaves applied work in place; the next
                # tick re-solves from the surviving state
                continue

    t = threading.Thread(target=loop, name="keystone-fleet-reactor",
                         daemon=True)
    t.start()
    return t
