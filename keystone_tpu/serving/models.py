"""The model-record layer of the serving plane.

Split out of ``serving/plane.py`` (the fleet PR): everything that
describes ONE served model — the live :class:`ServedModel` record with
its QPS window and LRU-with-cost retention value, the host-side
:class:`_EvictedModel` remainder the canonical-bytes contract keeps for
bit-identical readmission, and the pure helpers admission/warmup use
(zeros batches, weight-dtype narrowing, the non-finite guard, the drift
baseline probe). ``plane.py`` keeps the orchestration (admission
control, the worker, the publish discipline); the fleet placement
solver (``serving/placement.py``) and the migration reactor
(``serving/fleet.py``) consume these records without importing the
whole plane.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Optional, Tuple

import jax
import numpy as np

from ..observability.metrics import MetricsRegistry
from .residency import ModelCharge

#: seconds of request history the QPS estimate looks back over
_QPS_WINDOW_S = 30.0


@dataclass
class ServedModel:
    """One warm resident model. Mutable serving stats are only touched
    under the owning plane's lock (the plane declares the guard; this
    record carries no lock of its own)."""

    name: str
    fitted: Any                      # the working FittedPipeline
    blob: bytes                      # canonical pickle (readmission source)
    sample: Any                      # ShapeDtypeStruct pytree of ONE item
    charge: ModelCharge
    buckets: Tuple[int, ...]
    weight_dtype: Optional[str] = None
    ready: bool = False
    warmup_s: float = 0.0
    last_used_s: float = field(default_factory=time.perf_counter)
    served_rows: int = 0
    served_requests: int = 0
    batches: int = 0
    baseline: Any = None             # DriftBaseline or None
    drift_disabled: bool = False
    _recent: Deque[Tuple[float, int]] = field(default_factory=deque)

    def note_served(self, rows: int, requests: int, now: float) -> None:
        self.last_used_s = now
        self.served_rows += rows
        self.served_requests += requests
        self.batches += 1
        self._recent.append((now, rows))
        while self._recent and self._recent[0][0] < now - _QPS_WINDOW_S:
            self._recent.popleft()

    def qps(self, now: Optional[float] = None) -> float:
        """Observed rows/sec over the recent window (0 before any
        traffic) — the demand half of the retention value."""
        if not self._recent:
            return 0.0
        now = time.perf_counter() if now is None else now
        t0 = self._recent[0][0]
        span = max(now - t0, 1e-3)
        return sum(r for _, r in self._recent) / span

    def retention_value(self, now: Optional[float] = None) -> float:
        """LRU-with-cost: observed QPS x recompute (warmup) cost, with
        recency as an epsilon tiebreak so two idle models evict
        least-recently-used first."""
        return (self.qps(now) * max(self.warmup_s, 1e-3)
                + 1e-9 * self.last_used_s)

    def state(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "ready": self.ready,
            "weight_dtype": self.weight_dtype,
            "charge_nbytes": self.charge.total_nbytes(),
            "charge_source": self.charge.source,
            "buckets": list(self.buckets),
            "warmup_s": round(self.warmup_s, 4),
            "served_rows": self.served_rows,
            "served_requests": self.served_requests,
            "batches": self.batches,
            "qps": round(self.qps(), 3),
            "drift_baseline": self.baseline is not None
            and not self.drift_disabled,
        }


@dataclass
class _EvictedModel:
    """Host-side remainder of an evicted model: everything readmission
    needs to restore bit-identical serving."""

    blob: bytes
    sample: Any
    weight_dtype: Optional[str]
    evicted_s: float = field(default_factory=time.perf_counter)


def _count_nonfinite(outputs: Any) -> int:
    """Non-finite values in a host output pytree (float leaves only —
    an integer wire cannot carry NaN). One vectorized pass per leaf:
    the poisoned-batch guard's whole cost."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(outputs):
        arr = np.asarray(leaf)
        if arr.size and np.issubdtype(arr.dtype, np.floating):
            total += int(arr.size) - int(np.isfinite(arr).sum())
    return total


def _zeros_batch(sample: Any, rows: int) -> Any:
    return jax.tree_util.tree_map(
        lambda leaf: np.zeros((rows,) + tuple(leaf.shape),
                              np.dtype(leaf.dtype)),
        sample,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))


def _apply_weight_dtype(graph: Any, weight_dtype: Optional[str]) -> int:
    """Narrow every quantizable mapper in ``graph`` that did not choose
    a dtype itself (explicit per-model choices always win). Mirrors the
    LinearMapper constructor's constraint: only a plain (or absent)
    StandardScalerModel feature scaler keeps the quantized apply one
    fused affine program — other scalers stay f32 rather than raise."""
    from ..nodes.learning.linear import (
        BlockLinearMapper,
        LinearMapper,
        StandardScalerModel,
        _canon_weight_dtype,
    )

    wd = _canon_weight_dtype(weight_dtype)
    if wd is None:
        return 0
    changed = 0
    for node in graph.nodes:
        op = graph.get_operator(node)
        if not isinstance(op, (LinearMapper, BlockLinearMapper)):
            continue
        if op.weight_dtype is not None:
            continue
        scaler = getattr(op, "feature_scaler", None)
        if scaler is not None and type(scaler) is not StandardScalerModel:
            continue
        op.weight_dtype = wd
        # drop memoized programs/eq keys: the quantized apply is a
        # different program family (struct keys carry weight_dtype)
        for attr in [k for k in op.__dict__ if k.startswith("_jit_")]:
            del op.__dict__[attr]
        op.__dict__.pop("_eq_key_val", None)
        changed += 1
    return changed


def _evicted_record(entry: ServedModel) -> _EvictedModel:
    """Host-side remainder for one eviction (also counts it); the dict
    mutations stay inline at the call sites, under the plane lock."""
    MetricsRegistry.get_or_create().counter(
        "serving.evictions_total").inc()
    return _EvictedModel(blob=entry.blob, sample=entry.sample,
                         weight_dtype=entry.weight_dtype)


def _find_baseline(graph: Any) -> Any:
    """First fit-time drift sketch riding the fitted operators
    (``model.numerics_baseline``, attached by ``fit_streaming``)."""
    for node in graph.nodes:
        baseline = getattr(graph.get_operator(node),
                           "numerics_baseline", None)
        if baseline is not None:
            return baseline
    return None
