"""The serving chaos-scenario catalogue: seeded traffic + seeded
faults + asserted SLO FLOORS.

Each scenario is one reproducible experiment against a REAL
:class:`~keystone_tpu.serving.plane.ServingPlane` (warm executables,
the bounded queue, the worker thread — nothing mocked): a
:class:`~keystone_tpu.serving.loadgen.LoadSpec` builds the traffic, an
optional :class:`~keystone_tpu.resilience.faults.FaultPlan` builds the
weather, and the scenario asserts per-scenario p99/availability
FLOORS the way quantization parity is asserted — a number the run must
beat, not a vibe. Every floor violation produces a post-mortem
artifact (metrics snapshot + flight-recorder trace + reservoir
exemplars, ``observability/postmortem.py``) NAMING the scenario and
seed, so the repro is one command away.

Beyond the floors, :func:`run_scenario` enforces the substrate
invariants every run must keep:

* **clean-or-classified** — zero ``unclassified`` outcomes: under
  injected faults every request ends in a KNOWN verdict (ok / 429 /
  shed / poisoned / 404 / 503 / classified error);
* **zero wedged workers** — after replay, a probe request to every
  ready model must still resolve and ``close()`` must join the worker;
* **no dispatch past a deadline** — a request already expired when its
  batch reached the worker must carry ``DeadlineExpiredError``, never
  a result (checked per batch via the dispatch-guard wrapper).

The catalogue (see each module's docstring): ``burst``, ``diurnal``,
``zipf_churn``, ``straggler_dispatch``, ``poisoned_batch``,
``overload_shed``, plus the fleet pair (``replica_death``,
``migration_under_load`` — N replicas behind the real-HTTP router via
a scenario-owned ``run_fn`` substrate). ``tools/chaos_gate.py`` runs
all of them at bounded seeds in CI; the ``serving_soak`` bench section
emits their p99/availability as ``soak_<scenario>_*`` lines for
benchdiff.

Scenario planes share one (d, k) model family and bucket ladder on
purpose: the global JIT caches make every warmup after the first a
cache hit, so the whole catalogue runs in CI time.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...observability.metrics import MetricsRegistry
from ...observability.postmortem import dump_postmortem
from ...observability.slo import SloPolicy
from ...resilience.faults import FaultPlan
from ..batcher import DeadlineExpiredError
from ..loadgen import LoadSpec, LoadTrace, ReplayReport, generate_trace, replay

#: one model family for the whole catalogue (see module docstring)
MODEL_D, MODEL_K = 6, 2
MAX_BATCH = 8


@dataclass(frozen=True)
class Floors:
    """The per-scenario SLO floors a run must beat: p99 of OK requests
    (ms, CPU-sim generous — the gate catches regressions in KIND, the
    bench bands the numbers) and accepted-request availability."""

    p99_ms: float
    availability: float


@dataclass(frozen=True)
class Scenario:
    """One catalogue entry. ``spec_fn(seed)`` builds the traffic;
    ``plan_fn(seed)`` the fault plan (None = fair weather);
    ``check(result)`` returns EXTRA violation strings (scenario-
    specific invariants: 'rejections carried Retry-After', 'worker
    survived the poisoned batch', ...)."""

    name: str
    describe: str
    floors: Floors
    spec_fn: Callable[[int], LoadSpec]
    plan_fn: Callable[[int], Optional[FaultPlan]] = lambda seed: None
    check: Optional[Callable[["ScenarioResult"], List[str]]] = None
    queue_depth: int = 64
    submit_timeout_s: float = 0.25
    senders: int = 6
    #: a scenario that brings its own substrate (the fleet scenarios
    #: run N planes behind real HTTP instead of one in-process plane):
    #: ``run_fn(scenario, trace, seed, time_scale, violations)`` owns
    #: build/replay/teardown and returns ``(report, injections)``; the
    #: harness keeps the shared epilogue (floors, clean-or-classified,
    #: chaos.* counters, the post-mortem) so every catalogue entry is
    #: judged identically. None = the standard single-plane substrate.
    run_fn: Optional[Callable[
        ["Scenario", LoadTrace, int, float, List[str]],
        Tuple[ReplayReport, int]]] = None


@dataclass
class ScenarioResult:
    """One run's verdict: the replay report, the floors it was judged
    against, every violation (empty = CLEAN), and — when violated —
    the post-mortem artifact path naming scenario and seed."""

    scenario: str
    seed: int
    floors: Floors
    report: ReplayReport
    p99_ms: float
    availability: float
    injections: int
    violations: List[str] = field(default_factory=list)
    postmortem_path: Optional[str] = None
    wall_s: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.violations

    def summary(self) -> Dict[str, Any]:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "clean": self.clean,
            "violations": list(self.violations),
            "p99_ms": round(self.p99_ms, 3),
            "availability": round(self.availability, 4),
            "floors": {"p99_ms": self.floors.p99_ms,
                       "availability": self.floors.availability},
            "injections": self.injections,
            "postmortem": self.postmortem_path,
            "report": self.report.summary(),
        }


#: the catalogue; populated by the scenario modules at import
SCENARIOS: Dict[str, Scenario] = {}


def register(scenario: Scenario) -> Scenario:
    if scenario.name in SCENARIOS:
        raise ValueError(f"scenario {scenario.name!r} already registered")
    SCENARIOS[scenario.name] = scenario
    return scenario


def _fit_catalogue_model(seed: int) -> Any:
    """One tiny fitted pipeline of the shared (d, k) family — every
    scenario model has the same shapes, so warmup executables come from
    the global JIT cache after the first plane."""
    from ...nodes.learning.linear import LinearMapEstimator
    from ...parallel.dataset import ArrayDataset

    r = np.random.RandomState(1000 + seed)
    X = r.rand(48, MODEL_D).astype(np.float32)
    Y = r.rand(48, MODEL_K).astype(np.float32)
    return LinearMapEstimator(lam=1e-3).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()


def _input_for(model: str, n: int) -> np.ndarray:
    # deterministic-by-(model, n) payloads: cheap, finite, shaped right
    return np.full((n, MODEL_D), 0.5, dtype=np.float32)


def _guard_dispatch(plane: Any, violations: List[str]) -> None:
    """Wrap the plane worker's batch entry point with the no-dispatch-
    past-deadline check: any request ALREADY expired when its batch
    reached the worker must end in DeadlineExpiredError — a result
    would mean the plane burned device time on an answer nobody can
    use. Harness-only wrapper; the production path is untouched."""
    import jax  # noqa: F401  (plane already imported it)

    orig = plane._serve_batch

    def checked(requests):
        now = time.perf_counter()
        expired = [r for r in requests if r.expired(now)]
        orig(requests)
        for r in expired:
            exc = r.future.exception() if r.future.done() else None
            if not isinstance(exc, DeadlineExpiredError):
                violations.append(
                    "deadline_dispatch: request for "
                    f"{r.model!r} was expired on batch entry but got "
                    f"{type(exc).__name__ if exc else 'a result'} "
                    "instead of DeadlineExpiredError")

    plane._serve_batch = checked


def run_scenario(name: str, seed: int, time_scale: float = 1.0,
                 duration_s: Optional[float] = None) -> ScenarioResult:
    """Run one catalogue scenario at one seed; see module docstring.
    ``duration_s`` overrides the spec's window (tests shrink it);
    ``time_scale`` stretches the arrival clock without touching the
    event sequence."""
    import dataclasses

    from ..plane import ServingPlane

    scenario = SCENARIOS.get(name)
    if scenario is None:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(know {sorted(SCENARIOS)})")
    spec = scenario.spec_fn(seed)
    if duration_s is not None:
        spec = dataclasses.replace(spec, duration_s=float(duration_s))
        churn = tuple(c for c in spec.churn if c.t_s < spec.duration_s)
        spec = dataclasses.replace(spec, churn=churn)
    trace = generate_trace(spec)
    reg = MetricsRegistry.get_or_create()
    violations: List[str] = []
    t_run = time.perf_counter()

    if scenario.run_fn is not None:
        # custom substrate (fleet scenarios); the epilogue below still
        # judges the result exactly like every other catalogue entry
        report, injections = scenario.run_fn(scenario, trace, seed,
                                             time_scale, violations)
    else:
        # a live SLO policy sized to the scenario window, so the SLO
        # plane (rolling windows, burn rate, its own post-mortems) is
        # exercised by every run rather than idling at defaults
        plane = ServingPlane(
            max_batch=MAX_BATCH, queue_depth=scenario.queue_depth,
            slo_policy=SloPolicy(
                latency_threshold_ms=scenario.floors.p99_ms,
                availability_target=0.5, window=256,
                min_count=64),
            postmortem_min_interval_s=0.0)
        _guard_dispatch(plane, violations)
        plane.start()
        worker = None
        plan = scenario.plan_fn(seed)
        injections = 0
        try:
            for model in spec.models:
                plane.admit(model, _fit_catalogue_model(seed),
                            (np.zeros((MODEL_D,), np.float32)))
            worker = plane._worker
            if plan is not None:
                with plan:
                    report = replay(
                        trace, plane, _input_for,
                        senders=scenario.senders,
                        time_scale=time_scale,
                        submit_timeout_s=scenario.submit_timeout_s)
                injections = plan.injections()
            else:
                report = replay(
                    trace, plane, _input_for,
                    senders=scenario.senders,
                    time_scale=time_scale,
                    submit_timeout_s=scenario.submit_timeout_s)

            # zero-wedged-workers probe: every READY resident must
            # still answer (the queue drains, the worker is alive)
            for model in list(plane._live):
                try:
                    plane.predict(model, _input_for(model, 1),
                                  timeout_s=10.0)
                except BaseException as exc:
                    violations.append(
                        f"wedged_worker: post-chaos probe for "
                        f"{model!r} failed: "
                        f"{type(exc).__name__}: {exc}")
        finally:
            plane.close()
        if worker is not None and worker.is_alive():
            violations.append(
                "wedged_worker: the plane worker thread survived "
                "close() — the queue is wedged")

    p99 = report.p99_ms()
    availability = report.availability()
    if report.outcomes["unclassified"]:
        violations.append(
            f"unclassified: {report.outcomes['unclassified']} requests "
            f"ended in UNKNOWN verdicts (sample: {report.errors[:3]})")
    if p99 > scenario.floors.p99_ms:
        violations.append(
            f"p99_floor: p99 {p99:.1f} ms breached the "
            f"{scenario.floors.p99_ms:.0f} ms floor")
    if availability < scenario.floors.availability:
        violations.append(
            f"availability_floor: availability {availability:.4f} fell "
            f"below the {scenario.floors.availability} floor")

    result = ScenarioResult(
        scenario=name, seed=seed, floors=scenario.floors, report=report,
        p99_ms=p99, availability=availability, injections=injections,
        violations=violations, wall_s=time.perf_counter() - t_run)
    if scenario.check is not None:
        violations.extend(scenario.check(result))

    reg.counter("chaos.runs_total").inc()
    reg.counter("chaos.injections_total").inc(injections)
    if violations:
        reg.counter("chaos.violations_total").inc()
        # the post-mortem NAMES scenario and seed: the full repro is
        # `run_scenario(scenario, seed)` — nothing else varies
        result.postmortem_path = dump_postmortem(
            "chaos_scenario_violation",
            context={"scenario": name, "seed": seed,
                     "violations": list(violations),
                     "floors": {"p99_ms": scenario.floors.p99_ms,
                                "availability":
                                    scenario.floors.availability},
                     "p99_ms": p99, "availability": availability,
                     "report": report.summary()})
    else:
        reg.counter("chaos.clean_total").inc()
    return result


def load_catalogue() -> Dict[str, Scenario]:
    """Import every scenario module (idempotent) and return the
    registry — the one entry point the gate, the bench, and the tests
    share."""
    from . import (burst, diurnal, fleet_chaos, overload_shed,  # noqa: F401
                   poisoned_batch, straggler_dispatch, zipf_churn)

    return SCENARIOS
