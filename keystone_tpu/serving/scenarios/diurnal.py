"""``diurnal`` — sinusoidal rate swing over the window.

The traffic a long-lived deployment actually sees, compressed: the
arrival rate sweeps from trough to peak and back within the window
(thinned Poisson). The floors assert the plane holds its tail through
the peak without shedding classified-error blood — a p99 that only
looks good at the trough is exactly what gating on averages would
hide (PERFORMANCE.md rule 18).
"""
from __future__ import annotations

from typing import List

from ..loadgen import LoadSpec
from . import Floors, Scenario, ScenarioResult, register


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=1.6, rate_rps=220.0, arrival="diurnal",
        models=("diurnal_a", "diurnal_b"), zipf_s=1.2, sizes=(1, 2, 4),
        diurnal_amp=0.8, diurnal_period_s=1.6)


def _check(result: ScenarioResult) -> List[str]:
    out = []
    if result.report.outcomes["ok"] == 0:
        out.append("no_traffic: zero OK requests over the diurnal window")
    return out


register(Scenario(
    name="diurnal",
    describe="sinusoidal rate swing (trough->peak->trough), 2 models",
    floors=Floors(p99_ms=400.0, availability=0.97),
    spec_fn=_spec,
    check=_check,
))
