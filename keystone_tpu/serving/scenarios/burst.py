"""``burst`` — correlated arrival spikes against the bounded queue.

Fair-weather chaos: no injected faults, but the on/off arrival process
slams the queue with multi-request bursts that the pad-to-bucket
coalescer must absorb. The floors assert the plane rides bursts out
with high availability (the bounded queue may 429 the worst spike —
an honest, classified verdict — but never an unclassified failure).
"""
from __future__ import annotations

from typing import List

from ..loadgen import LoadSpec
from . import Floors, Scenario, ScenarioResult, register


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=1.5, rate_rps=260.0, arrival="bursty",
        models=("burst_a", "burst_b"), zipf_s=1.1, sizes=(1, 2, 4),
        burst_mult=4.0, burst_on_s=0.2, burst_off_s=0.2)


def _check(result: ScenarioResult) -> List[str]:
    out = []
    if result.report.outcomes["ok"] == 0:
        out.append("no_traffic: zero OK requests — the burst never "
                   "reached the plane")
    return out


register(Scenario(
    name="burst",
    describe="on/off arrival bursts, 2 models, fair weather",
    floors=Floors(p99_ms=400.0, availability=0.97),
    spec_fn=_spec,
    check=_check,
))
