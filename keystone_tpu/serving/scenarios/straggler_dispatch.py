"""``straggler_dispatch`` — a slow device, not a broken one.

Seeded ``straggler`` faults at ``serve.dispatch`` stretch a fraction
of batch dispatches by a fixed dwell — the slow-batch tail a
contended accelerator produces. Nothing errors: every request still
succeeds, but the tail moves. The floors assert the p99 stays bounded
(coalescing keeps the straggler's blast radius to its own batch) and
availability stays at fair-weather levels — a straggler is a latency
event, never an availability event.
"""
from __future__ import annotations

from typing import List, Optional

from ...resilience.faults import FaultPlan
from ..loadgen import LoadSpec
from . import Floors, Scenario, ScenarioResult, register


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=1.5, rate_rps=200.0, arrival="poisson",
        models=("straggler_a", "straggler_b"), zipf_s=1.1,
        sizes=(1, 2, 4))


def _plan(seed: int) -> Optional[FaultPlan]:
    return (FaultPlan(seed=seed)
            .add("serve.dispatch", kind="straggler", rate=0.15,
                 delay_s=0.08))


def _check(result: ScenarioResult) -> List[str]:
    out = []
    if result.injections < 1:
        out.append("no_injection: zero straggler dispatches fired")
    rep = result.report
    failed = (rep.outcomes["error"] + rep.outcomes["poisoned"]
              + rep.outcomes["unclassified"])
    if failed:
        out.append(f"straggler_broke_requests: {failed} requests "
                   "FAILED under straggler faults — a slow batch must "
                   "stay a latency event, not an availability event")
    return out


register(Scenario(
    name="straggler_dispatch",
    describe="15% of dispatches stretched 80 ms (seeded stragglers); "
             "tail bounded, availability untouched",
    floors=Floors(p99_ms=600.0, availability=0.99),
    spec_fn=_spec,
    plan_fn=_plan,
    check=_check,
))
