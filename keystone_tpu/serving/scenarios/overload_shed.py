"""``overload_shed`` — sustained overload against graceful degradation.

The arrival rate is far past the plane's capacity, the queue is
deliberately shallow, and every request carries a deadline. The
graceful-degradation contract under that weather:

* the slot gate 429s the overflow FAST (short submit timeout), every
  rejection carrying a drain-rate ``Retry-After`` hint;
* requests that slip in but exceed their deadline while queued are
  SHED before dispatch — zero device time burned on answers nobody
  can use (the dispatch-guard wrapper checks every batch);
* the requests that ARE served stay fast: the p99 floor applies to
  the survivors, because a "successful" request slower than the
  deadline is the same failure with better manners.

Availability is honestly low here — the floor asserts the plane keeps
serving SOMETHING (no collapse-to-zero), while the rejected/shed
verdicts stay classified.
"""
from __future__ import annotations

from typing import List, Optional

from ...resilience.faults import FaultPlan
from ..loadgen import LoadSpec
from . import Floors, Scenario, ScenarioResult, register


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=0.8, rate_rps=1200.0, arrival="bursty",
        models=("overload_a",), zipf_s=1.0, sizes=(1, 2),
        burst_mult=3.0, burst_on_s=0.3, burst_off_s=0.1,
        deadline_ms=150.0)


def _plan(seed: int) -> Optional[FaultPlan]:
    # dispatch latency makes the overload bite on CPU sim: each batch
    # pays 100 ms, so capacity is ~50 rps against a 1200 rps schedule.
    # The replay senders are closed-loop (in-flight <= senders), so
    # the scenario's senders (16) deliberately exceed queue_depth (8):
    # the queue backs up past the 150 ms deadline and the slot gate
    # actually runs dry.
    return (FaultPlan(seed=seed)
            .add("serve.dispatch", kind="latency", delay_s=0.10))


def _check(result: ScenarioResult) -> List[str]:
    out = []
    rep = result.report
    if rep.outcomes["rejected"] == 0:
        out.append("no_backpressure: sustained overload produced zero "
                   "429s — the slot gate is not bounding the queue")
    if rep.outcomes["rejected"] and rep.retry_after_seen == 0:
        out.append("no_retry_after: 429s carried no Retry-After hint")
    if rep.outcomes["shed"] == 0:
        out.append("no_shedding: no queued request was deadline-shed "
                   "under overload — expired work burned device time")
    if rep.outcomes["ok"] == 0:
        out.append("collapse: zero requests served under overload — "
                   "shedding must degrade, not kill")
    return out


register(Scenario(
    name="overload_shed",
    describe="3x-capacity bursts into a shallow queue with 150 ms "
             "deadlines: fast 429s w/ Retry-After, pre-dispatch sheds, "
             "survivors stay fast",
    floors=Floors(p99_ms=400.0, availability=0.10),
    spec_fn=_spec,
    plan_fn=_plan,
    check=_check,
    queue_depth=8,
    submit_timeout_s=0.05,
    senders=16,
))
