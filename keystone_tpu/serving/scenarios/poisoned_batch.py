"""``poisoned_batch`` — NaN born between enqueue and collect.

Seeded ``corrupt`` faults at ``serve.dispatch`` poison the MERGED
batch value (the default NaN mutation) on two visits. The plane's
nonfinite guard must catch each poisoned batch at collect time and
fail EXACTLY that batch's requests with a classified
``PoisonedBatchError`` (500, post-mortem attached) — never hand a
client silent NaN predictions, and never wedge the worker: the very
next batch must serve clean. The availability floor prices in the two
lost batches; the checks assert the classification and the recovery.
"""
from __future__ import annotations

from typing import List, Optional

from ...resilience.faults import FaultPlan
from ..loadgen import LoadSpec
from . import Floors, Scenario, ScenarioResult, register


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=1.5, rate_rps=200.0, arrival="poisson",
        models=("poison_a", "poison_b"), zipf_s=1.1, sizes=(1, 2, 4))


def _plan(seed: int) -> Optional[FaultPlan]:
    # two poisoned batches, after the traffic is flowing (the warmup
    # zeros-batches must not eat the injections: corrupt rules only
    # fire at the value-carrying _serve_batch site, so `after` counts
    # real batches)
    return (FaultPlan(seed=seed)
            .add("serve.dispatch", kind="corrupt", after=3, count=2))


def _check(result: ScenarioResult) -> List[str]:
    out = []
    rep = result.report
    if result.injections < 1:
        out.append("no_injection: zero batches were poisoned")
    if rep.outcomes["poisoned"] == 0 and result.injections:
        out.append("unclassified_poison: batches were poisoned but no "
                   "request ended in PoisonedBatchError — the "
                   "nonfinite guard did not classify")
    if rep.outcomes["poisoned"] and not rep.postmortems:
        out.append("no_postmortem: poisoned requests carried no "
                   "post-mortem path")
    if rep.outcomes["ok"] == 0:
        out.append("no_recovery: zero OK requests — the worker did "
                   "not survive the poisoned batch")
    return out


register(Scenario(
    name="poisoned_batch",
    describe="2 seeded NaN-poisoned batches; classified 500s with "
             "post-mortems, worker survives, next batch clean",
    floors=Floors(p99_ms=400.0, availability=0.80),
    spec_fn=_spec,
    plan_fn=_plan,
    check=_check,
))
