"""``zipf_churn`` — skewed popularity + admit/evict/readmit under live
load, with an injected admission fault mid-warmup.

Three models under Zipf popularity; the churn driver evicts and
readmits the tail models while the hot model keeps serving. One
injected ``serve.admit`` fault lands MID-WARMUP during a readmission:
the admission must roll back atomically (nothing half-registered, the
ledger released, the fence re-armed) and the NEXT readmission of the
same model must succeed — the rollback-then-retry path under real
traffic. Requests racing the churn get honest routing verdicts (404
not-admitted / 503 warming), which are classifications, not failures.
"""
from __future__ import annotations

from typing import List, Optional

from ...resilience.faults import FaultPlan
from ..loadgen import ChurnEvent, LoadSpec
from . import Floors, Scenario, ScenarioResult, register

_MODELS = ("churn_hot", "churn_warm", "churn_cold")


def _spec(seed: int) -> LoadSpec:
    return LoadSpec(
        seed=seed, duration_s=2.0, rate_rps=180.0, arrival="poisson",
        models=_MODELS, zipf_s=1.4, sizes=(1, 2, 4),
        churn=(
            ChurnEvent(t_s=0.35, action="evict", model="churn_cold"),
            ChurnEvent(t_s=0.70, action="readmit", model="churn_cold"),
            ChurnEvent(t_s=1.00, action="evict", model="churn_warm"),
            ChurnEvent(t_s=1.25, action="readmit", model="churn_warm"),
            # the retry after the injected mid-warmup failure below
            ChurnEvent(t_s=1.55, action="readmit", model="churn_warm"),
        ))


def _plan(seed: int) -> Optional[FaultPlan]:
    # one admission fault, landing mid-warmup of churn_warm's t=1.25
    # readmission — the t=1.55 churn event retries it. The plan is
    # installed around replay() only, so the startup admissions do not
    # count: the first serve.admit visits belong to churn_cold's
    # readmit (1 pre-mutation + 1 per warmup bucket), then churn_warm's
    # readmit follows. after=visits_before+2 skips churn_cold's full
    # pass plus churn_warm's pre-mutation and first bucket, firing on
    # the SECOND warmup bucket — genuinely mid-warmup.
    from ..batcher import BucketPolicy
    from . import MAX_BATCH

    buckets_per_admit = len(BucketPolicy(MAX_BATCH).rows(1))
    visits_before = 1 + buckets_per_admit
    return (FaultPlan(seed=seed)
            .add("serve.admit", kind="error",
                 after=visits_before + 2, count=1))


def _check(result: ScenarioResult) -> List[str]:
    out = []
    rep = result.report
    if result.injections < 1:
        out.append("no_injection: the mid-warmup admission fault "
                   "never fired")
    if rep.churn_failed < 1:
        out.append("no_rollback: the injected admission fault did not "
                   "surface as a failed churn action")
    if rep.churn_applied < 3:
        out.append(f"churn_stalled: only {rep.churn_applied} churn "
                   "actions applied — eviction/readmission wedged")
    if rep.outcomes["ok"] == 0:
        out.append("no_traffic: zero OK requests under churn")
    return out


register(Scenario(
    name="zipf_churn",
    describe="Zipf popularity, evict/readmit under load, one injected "
             "mid-warmup admission fault (atomic rollback + retry)",
    floors=Floors(p99_ms=500.0, availability=0.90),
    spec_fn=_spec,
    plan_fn=_plan,
    check=_check,
))
