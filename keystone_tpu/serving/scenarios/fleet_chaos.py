"""Fleet chaos: ``replica_death`` and ``migration_under_load``.

Both scenarios bring their own substrate (``Scenario.run_fn``): N
in-process serving planes, each behind a REAL HTTP replica server
(predict + admin surfaces), fronted by the real-HTTP fleet router —
requests travel loadgen -> router socket -> replica socket -> plane,
the same wire path production takes, all inside the gate process so
CI time stays bounded and the JIT caches stay shared.

``replica_death`` — the tentpole drill: mid-replay, the replica
hosting the routing table's models is killed COLD (server down, plane
closed, no drain), the reactor's next probe notices, counts
``fleet.replica_deaths_total``, re-solves placement over the
survivors, and re-admits the lost models from the controller's
canonical bytes (sha-verified). The floors assert the p99 spike stays
bounded and the availability dip stays classified: every request that
died with the replica ends as a counted 503/429/error verdict — zero
unclassified damage.

``migration_under_load`` — the placement churn drill: while traffic
flows, the controller learns one model went hot (``note_demand``),
rebalances (replicating it — admission under live load), then DRAINS a
replica (admit on target -> sha verify -> evict on source, capacity
double-charged never zero-charged) and scales back up. The checks
assert the moves actually happened (``router.rebalance_total``
advanced), every migrated copy was bit-identical (any sha mismatch
raises and fails the run), and the fleet still answers for every model
afterwards.

Both scenarios assert the shared catalogue invariants through the
standard harness epilogue — floors, clean-or-classified, chaos.*
counters, post-mortem on violation.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Tuple

import numpy as np

from ...observability.metrics import MetricsRegistry
from ...observability.slo import SloPolicy
from ..loadgen import (HttpServingClient, LoadSpec, LoadTrace,
                       ReplayReport, replay)
from . import (MAX_BATCH, MODEL_D, Floors, Scenario, ScenarioResult,
               _fit_catalogue_model, _input_for, register)

#: the fleet scenarios' model family: three names, one hot
_MODELS = ("hot", "warm", "cold")


def _build_fleet(scenario: Scenario, seed: int, n_replicas: int,
                 hot_qps: float):
    """N planes, each behind a real-HTTP replica server, fronted by
    the real-HTTP router; models registered with the controller and
    placed via the solver under FINITE per-replica budgets (sized to
    ~3.3 model charges, so replication is an earned placement decision
    with real scarcity, not an unbounded spray). Returns everything
    teardown needs."""
    from ..fleet import FleetController
    from ..plane import ServingPlane
    from ..replica import serve_replica
    from ..router import FleetRouter, HttpReplicaClient, serve_router

    planes, servers, clients = [], [], []
    for i in range(n_replicas):
        plane = ServingPlane(
            max_batch=MAX_BATCH, queue_depth=scenario.queue_depth,
            slo_policy=SloPolicy(
                latency_threshold_ms=scenario.floors.p99_ms,
                availability_target=0.5, window=256, min_count=64),
            postmortem_min_interval_s=0.0)
        plane.start()
        server = serve_replica(plane)
        planes.append(plane)
        servers.append(server)
        clients.append(HttpReplicaClient(
            f"r{i}", "127.0.0.1", server.server_port,
            stats_ttl_s=0.05))
    router = FleetRouter(clients, spill_queue_depth=max(
        scenario.queue_depth // 2, 4))
    controller = FleetController(router)
    fitted = _fit_catalogue_model(seed)
    sample = np.zeros((MODEL_D,), np.float32)
    hot = controller.register("hot", fitted, sample, qps=hot_qps,
                              warmup_s=1.0)
    controller.register("warm", fitted, sample, qps=60.0, warmup_s=0.5)
    controller.register("cold", fitted, sample)
    for client in clients:
        controller.set_budget(client.replica_id,
                              3.3 * hot.charge_nbytes)
    controller.rebalance()
    router_server = serve_router(router)
    return planes, servers, clients, router, controller, router_server


def _teardown(planes, servers, router_server) -> None:
    # the scenario may have already killed a server/plane mid-run:
    # a second shutdown/close is allowed to find a corpse
    router_server.shutdown()
    for server in servers:
        try:
            server.shutdown()
        except (OSError, RuntimeError):
            pass
    for plane in planes:
        try:
            plane.close()
        except (OSError, RuntimeError):
            pass


def _probe_all(router, violations: List[str], label: str) -> None:
    """Every registered model must still answer through the router —
    the fleet's zero-wedged-workers invariant."""
    payload = json.dumps(
        {"instances": [[0.5] * MODEL_D]}).encode()
    for model in _MODELS:
        try:
            status, body, _ = router.predict_raw(model, payload)
        except BaseException as exc:
            violations.append(
                f"{label}: post-chaos probe for {model!r} raised "
                f"{type(exc).__name__}: {exc}")
            continue
        if status != 200:
            violations.append(
                f"{label}: post-chaos probe for {model!r} answered "
                f"{status}: {body[:120].decode(errors='replace')}")


def _replay_http(scenario: Scenario, trace: LoadTrace, port: int,
                 time_scale: float) -> ReplayReport:
    client = HttpServingClient("127.0.0.1", port)
    return replay(trace, client, _input_for,
                  senders=scenario.senders, time_scale=time_scale,
                  submit_timeout_s=scenario.submit_timeout_s)


# -- replica_death -----------------------------------------------------------

def _run_replica_death(scenario: Scenario, trace: LoadTrace, seed: int,
                       time_scale: float, violations: List[str]
                       ) -> Tuple[ReplayReport, int]:
    from ..fleet import FleetAutoscaler

    reg = MetricsRegistry.get_or_create()
    deaths_before = reg.counter("fleet.replica_deaths_total").value
    built = _build_fleet(scenario, seed, n_replicas=3, hot_qps=800.0)
    planes, servers, clients, router, controller, router_server = built
    autoscaler = FleetAutoscaler(controller, sustain_ticks=10**6)
    half_s = trace.spec.duration_s * time_scale * 0.5
    killed: Dict[str, Any] = {}

    def killer():
        time.sleep(half_s)
        # kill whichever replica hosts the MOST models: maximal
        # redistribution, no drain, no goodbye
        placement = controller.placement
        count: Dict[str, int] = {}
        for reps in placement.assignments.values():
            for rid in reps:
                count[rid] = count.get(rid, 0) + 1
        victim = max(sorted(count), key=lambda r: count[r])
        idx = clients.index(next(c for c in clients
                                 if c.replica_id == victim))
        servers[idx].shutdown()
        planes[idx].close()
        killed["victim"] = victim
        killed["models"] = count[victim]
        # the reactor's probe tick is the recovery path under test
        try:
            killed["action"] = autoscaler.tick()
        except BaseException as exc:
            violations.append(
                f"replica_death: recovery raised "
                f"{type(exc).__name__}: {exc}")

    thread = threading.Thread(target=killer, daemon=True,
                              name="chaos-replica-killer")
    thread.start()
    try:
        report = _replay_http(scenario, trace,
                              router_server.server_port, time_scale)
        thread.join(timeout=30.0)
        if killed.get("action") != "death":
            violations.append(
                "replica_death: the reactor tick did not classify the "
                f"kill as a death (got {killed.get('action')!r})")
        deaths = reg.counter("fleet.replica_deaths_total").value \
            - deaths_before
        if deaths != 1:
            violations.append(
                f"replica_death: expected exactly 1 counted death, "
                f"got {deaths:g}")
        victim = killed.get("victim")
        if victim is not None and victim in router.replica_ids():
            violations.append(
                f"replica_death: dead replica {victim!r} still in the "
                "routing membership")
        table = router.state()["models"]
        missing = [m for m in _MODELS if not table.get(m)]
        if missing:
            violations.append(
                f"replica_death: models {missing} unroutable after "
                "recovery — redistribution incomplete")
        _probe_all(router, violations, "replica_death")
    finally:
        _teardown(planes, servers, router_server)
    return report, 1  # one injected fault: the kill


def _check_replica_death(result: ScenarioResult) -> List[str]:
    out: List[str] = []
    # the dip must be CLASSIFIED: whatever the kill cost shows up as
    # counted rejected/error verdicts, never unclassified (the harness
    # already asserts unclassified == 0; here we assert the run
    # actually went THROUGH the outage rather than around it)
    if result.report.outcomes["ok"] == 0:
        out.append("replica_death: no request succeeded — the fleet "
                   "never served")
    return out


register(Scenario(
    name="replica_death",
    describe="kill the busiest of 3 replicas cold mid-replay; the "
             "reactor must notice, re-place its models from canonical "
             "bytes (sha-verified), and keep every refusal classified",
    floors=Floors(p99_ms=400.0, availability=0.90),
    spec_fn=lambda seed: LoadSpec(
        seed=900 + seed, duration_s=2.4, rate_rps=90.0,
        arrival="poisson", models=_MODELS, zipf_s=1.2,
        sizes=(1, 2, 4)),
    check=_check_replica_death,
    queue_depth=64,
    submit_timeout_s=0.25,
    senders=6,
    run_fn=_run_replica_death,
))


# -- migration_under_load ----------------------------------------------------

def _run_migration(scenario: Scenario, trace: LoadTrace, seed: int,
                   time_scale: float, violations: List[str]
                   ) -> Tuple[ReplayReport, int]:
    reg = MetricsRegistry.get_or_create()
    moves_before = reg.counter("router.rebalance_total").value
    # "hot" starts COLD (qps 0): the copy it gains mid-run must be
    # bought by the note_demand signal, not by initial placement
    built = _build_fleet(scenario, seed, n_replicas=2, hot_qps=0.0)
    planes, servers, clients, router, controller, router_server = built
    window_s = trace.spec.duration_s * time_scale
    done: Dict[str, Any] = {}

    def migrator():
        try:
            # 1/3 in: "hot" got hotter — rebalance replicates it onto
            # the second replica (admission + sha verify under load)
            time.sleep(window_s / 3.0)
            controller.note_demand("hot", qps=5000.0, warmup_s=2.0)
            controller.rebalance()
            done["replicated"] = len(
                controller.placement.replicas_for("hot"))
            # 2/3 in: drain r1 — every model it hosts migrates to r0
            # (admit -> verify -> evict), then r1 leaves the fleet
            time.sleep(window_s / 3.0)
            controller.drain_replica("r1")
            done["drained"] = True
        except BaseException as exc:
            violations.append(
                f"migration_under_load: {type(exc).__name__}: {exc}")

    thread = threading.Thread(target=migrator, daemon=True,
                              name="chaos-migrator")
    thread.start()
    try:
        report = _replay_http(scenario, trace,
                              router_server.server_port, time_scale)
        thread.join(timeout=30.0)
        if done.get("replicated", 0) < 2:
            violations.append(
                "migration_under_load: the hot model did not gain a "
                f"copy (copies: {done.get('replicated')})")
        if not done.get("drained"):
            violations.append(
                "migration_under_load: the drain never completed")
        if "r1" in router.replica_ids():
            violations.append(
                "migration_under_load: drained replica r1 is still "
                "in the fleet")
        moves = reg.counter("router.rebalance_total").value \
            - moves_before
        if moves < 2:
            violations.append(
                f"migration_under_load: expected >= 2 counted "
                f"rebalances (replicate + drain), got {moves:g}")
        table = router.state()["models"]
        missing = [m for m in _MODELS if not table.get(m)]
        if missing:
            violations.append(
                f"migration_under_load: models {missing} unroutable "
                "after the drain")
        _probe_all(router, violations, "migration_under_load")
    finally:
        _teardown(planes, servers, router_server)
    return report, 2  # two injected mutations: replicate + drain


register(Scenario(
    name="migration_under_load",
    describe="replicate a newly-hot model and drain a replica while "
             "traffic flows; every move admit->sha-verify->evict, "
             "zero unclassified outcomes",
    floors=Floors(p99_ms=400.0, availability=0.95),
    spec_fn=lambda seed: LoadSpec(
        seed=950 + seed, duration_s=2.4, rate_rps=80.0,
        arrival="bursty", models=_MODELS, zipf_s=1.3,
        sizes=(1, 2)),
    queue_depth=64,
    submit_timeout_s=0.25,
    senders=6,
    run_fn=_run_migration,
))
