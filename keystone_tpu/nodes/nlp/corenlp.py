"""Linguistic annotation nodes (reference ``nodes/nlp/CoreNLPFeatureExtractor
.scala:18-38``, ``POSTagger.scala:24-35``, ``NER.scala:20-31``).

The reference wraps external JVM model libraries (CoreNLP via
sista-processors, Epic CRF/SemiCRF). Those libraries have no TPU analogue
and no Python port in this image, so the node *surface* is kept — a
pluggable model object with ``best_sequence(words)`` — and small in-tree
rule-based English models provide working defaults. Heavier models (e.g.
a transformers pipeline on hosts that have one) plug in by implementing
the same one-method protocol.

These are host-stage transformers: tagging/lemmatization is ragged
string work that belongs on the host side of the DAG (SURVEY.md §7
"Host/device choreography for NLP").
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ...workflow.transformer import HostTransformer

# --------------------------------------------------------------- lemmatizer

#: Irregular English forms (closed list, the usual suspects).
_IRREGULAR = {
    "was": "be", "were": "be", "is": "be", "are": "be", "am": "be",
    "been": "be", "being": "be", "'s": "be", "'re": "be", "'m": "be",
    "has": "have", "had": "have", "having": "have", "'ve": "have",
    "does": "do", "did": "do", "done": "do", "doing": "do",
    "goes": "go", "went": "go", "gone": "go", "going": "go",
    "said": "say", "says": "say", "made": "make", "took": "take",
    "taken": "take", "came": "come", "saw": "see", "seen": "see",
    "knew": "know", "known": "know", "got": "get", "gotten": "get",
    "gave": "give", "given": "give", "found": "find", "thought": "think",
    "told": "tell", "became": "become", "left": "leave", "felt": "feel",
    "kept": "keep", "held": "hold", "brought": "bring", "bought": "buy",
    "wrote": "write", "written": "write", "ran": "run", "spoke": "speak",
    "spoken": "speak", "stood": "stand", "lost": "lose", "paid": "pay",
    "met": "meet", "sat": "sit", "led": "lead", "grew": "grow",
    "grown": "grow", "meant": "mean", "sent": "send", "built": "build",
    "spent": "spend", "fell": "fall", "fallen": "fall", "drew": "draw",
    "drawn": "draw", "broke": "break", "broken": "break", "wore": "wear",
    "worn": "wear", "chose": "choose", "chosen": "choose",
    "children": "child", "men": "man", "women": "woman",
    "people": "person", "mice": "mouse", "feet": "foot", "teeth": "tooth",
    "geese": "goose", "lives": "life", "wives": "wife", "knives": "knife",
    "leaves": "leaf", "selves": "self", "better": "good", "best": "good",
    "worse": "bad", "worst": "bad", "further": "far", "furthest": "far",
}

_VOWELS = set("aeiou")
_DOUBLE_OK = set("bdgklmnprt")  # consonants that double before -ing/-ed


def _undouble(stem: str) -> str:
    if (
        len(stem) >= 3
        and stem[-1] == stem[-2]
        and stem[-1] in _DOUBLE_OK
    ):
        return stem[:-1]
    return stem


def _needs_e(stem: str) -> bool:
    """mak+e, writ+e: single-syllable stem ending consonant-vowel-consonant
    (not w/x/y) — the Porter-style restore-e condition. Multi-syllable
    stems (visit+ed) keep no e."""
    if len(stem) < 3:
        return False
    a, b, c = stem[-3], stem[-2], stem[-1]
    if not (
        a not in _VOWELS
        and b in _VOWELS
        and c not in _VOWELS
        and c not in "wxy"
    ):
        return False
    vowel_groups = len(re.findall(r"[aeiou]+", stem))
    return vowel_groups == 1


def english_lemmatize(word: str, pos: Optional[str] = None) -> str:
    """Rule-based English lemmatizer: irregular table + suffix stripping
    with undoubling and CVC e-restoration. ``pos`` (a Penn-style tag)
    restricts -er/-est stripping to adjectives/adverbs."""
    w = word.lower()
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    n = len(w)
    if n > 4 and w.endswith("ies"):
        return w[:-3] + "y"
    if n > 4 and w.endswith(("ches", "shes", "sses", "xes", "zes")):
        return w[:-2]
    if n > 3 and w.endswith("s") and not w.endswith(("ss", "us", "is")):
        return w[:-1]
    # -ing stripping is gerund-only: a POS tag gates it exactly; absent
    # a tag, the -ing-noun exception list (morning, thing, ...) stands in
    ing_ok = (pos == "VBG") if pos is not None else (
        w not in _ING_EXCEPTIONS)  # defined with the tagger below
    if n > 5 and w.endswith("ying") and ing_ok:
        return w[:-4] + "y"
    if n > 4 and w.endswith("ing") and ing_ok:
        stem = _undouble(w[:-3])
        # a doubled consonant implies the base had no final e (run+ning)
        return stem + "e" if stem == w[:-3] and _needs_e(stem) else stem
    if n > 4 and w.endswith("ied"):
        return w[:-3] + "y"
    if n > 3 and w.endswith("ed"):
        stem = _undouble(w[:-2])
        if stem.endswith("e"):
            return stem
        return stem + "e" if stem == w[:-2] and _needs_e(stem) else stem
    if pos in ("JJR", "JJS", "RBR", "RBS"):
        if n > 4 and w.endswith("est"):
            return _undouble(w[:-3])
        if n > 3 and w.endswith("er"):
            return _undouble(w[:-2])
    return w


# --------------------------------------------------------------- POS tagger


@dataclass
class TaggedSequence:
    """Words + per-word tags (the Epic ``TaggedSequence`` analogue)."""

    words: List[str]
    tags: List[str]

    def pairs(self) -> List[Tuple[str, str]]:
        return list(zip(self.words, self.tags))


_CLOSED_CLASS = {
    "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
    "these": "DT", "those": "DT", "some": "DT", "any": "DT", "no": "DT",
    "each": "DT", "every": "DT",
    "of": "IN", "in": "IN", "on": "IN", "at": "IN", "by": "IN",
    "for": "IN", "with": "IN", "from": "IN", "to": "TO", "into": "IN",
    "over": "IN", "under": "IN", "about": "IN", "after": "IN",
    "before": "IN", "between": "IN", "through": "IN", "during": "IN",
    "against": "IN", "as": "IN",
    "i": "PRP", "you": "PRP", "he": "PRP", "she": "PRP", "it": "PRP",
    "we": "PRP", "they": "PRP", "me": "PRP", "him": "PRP", "her": "PRP",
    "us": "PRP", "them": "PRP",
    "my": "PRP$", "your": "PRP$", "his": "PRP$", "its": "PRP$",
    "our": "PRP$", "their": "PRP$",
    "and": "CC", "or": "CC", "but": "CC", "nor": "CC", "yet": "CC",
    "not": "RB", "n't": "RB", "very": "RB", "too": "RB", "also": "RB",
    "will": "MD", "would": "MD", "can": "MD", "could": "MD", "may": "MD",
    "might": "MD", "shall": "MD", "should": "MD", "must": "MD",
    "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD", "am": "VBP",
    "be": "VB", "been": "VBN", "being": "VBG",
    "has": "VBZ", "have": "VBP", "had": "VBD",
    "do": "VBP", "does": "VBZ", "did": "VBD",
    "who": "WP", "what": "WP", "which": "WDT", "where": "WRB",
    "when": "WRB", "why": "WRB", "how": "WRB",
    "there": "EX", "if": "IN", "because": "IN", "while": "IN",
    "than": "IN", "without": "IN", "outside": "IN", "inside": "IN",
    "near": "IN", "across": "IN",
    "then": "RB", "now": "RB", "here": "RB", "just": "RB", "only": "RB",
    "never": "RB", "always": "RB", "often": "RB", "still": "RB",
    "already": "RB", "again": "RB", "soon": "RB",
    "all": "DT", "both": "DT",
    "many": "JJ", "few": "JJ", "several": "JJ", "such": "JJ",
    "other": "JJ", "same": "JJ", "own": "JJ",
}

#: Penn punctuation tags; anything non-alphanumeric not listed is SYM.
_PUNCT_TAGS = {
    ".": ".", "!": ".", "?": ".", ",": ",", ";": ":", ":": ":",
    "--": ":", "-": ":", "(": "(", ")": ")", "``": "``", "''": "''",
    '"': "''", "'": "''", "$": "$", "&": "CC",
}

_NUMBER_RE = re.compile(r"^[+-]?(\d+([.,]\d+)*|\d+(st|nd|rd|th))$")

_ADJ_SUFFIXES = ("ous", "ful", "ive", "able", "ible", "al", "ic", "less")
_NOUN_SUFFIXES = ("tion", "sion", "ment", "ness", "ity", "ship", "hood",
                  "ism", "ist", "ance", "ence", "ure", "age")
#: -ing nouns / non-gerunds (so VBG never fires on them).
_ING_EXCEPTIONS = {
    "morning", "evening", "nothing", "something", "anything",
    "everything", "thing", "king", "ring", "spring", "string", "wing",
    "sing", "bring",
}
#: -en words that are NOT past participles (so VBN never fires).
_EN_EXCEPTIONS = {
    "garden", "kitchen", "chicken", "golden", "wooden", "open", "even",
    "seven", "eleven", "heaven", "oven", "often", "queen", "green",
    "screen", "between", "men", "women", "children", "citizen", "dozen",
    "pen", "ten", "then", "when",
}
#: -er words that are NOT comparatives (so JJR never fires on them).
_ER_EXCEPTIONS = {
    "other", "another", "over", "under", "after", "never", "ever",
    "together", "whether", "either", "neither", "however", "rather",
    "water", "corner", "number", "paper", "member", "letter", "center",
    "matter", "order", "power", "summer", "winter", "computer", "user",
    "server", "offer", "answer", "player", "teacher", "writer", "reader",
    "leader", "worker", "manager", "father", "mother", "brother",
    "sister", "daughter", "per", "her",
}


class RuleBasedPosModel:
    """Greedy lexicon + suffix + shape tagger (Penn-style tags): the
    in-tree default model for :class:`POSTagger`. Same one-method
    protocol as the reference's Epic CRF (``model.bestSequence``)."""

    def best_sequence(self, words: Sequence[str]) -> TaggedSequence:
        tags = []
        for i, word in enumerate(words):
            tags.append(self._tag(word, sentence_initial=(i == 0)))
        return TaggedSequence(list(words), tags)

    def _tag(self, word: str, sentence_initial: bool) -> str:
        w = word.lower()
        if not any(c.isalnum() for c in word):
            return _PUNCT_TAGS.get(word, "SYM")
        if _NUMBER_RE.match(word):
            return "CD"
        if w in _CLOSED_CLASS:
            return _CLOSED_CLASS[w]
        if word[:1].isupper() and not sentence_initial:
            plural = (
                len(w) > 4
                and w.endswith("s")
                and not w.endswith(("ss", "us", "is"))
            )
            return "NNPS" if plural else "NNP"
        if w.endswith("ly"):
            return "RB"
        if w.endswith("ing") and len(w) > 4 and w not in _ING_EXCEPTIONS:
            return "VBG"
        if w.endswith("ed") and len(w) > 3:
            return "VBD"
        if w.endswith("en") and len(w) > 3 and w not in _EN_EXCEPTIONS:
            return "VBN"
        if w.endswith(_ADJ_SUFFIXES):
            return "JJ"
        if w.endswith("est") and len(w) > 4:
            return "JJS"
        if (
            w.endswith("er")
            and len(w) > 4
            and w not in _ER_EXCEPTIONS
            and not w.endswith(("ier", "eer"))
        ):
            # likely comparative (faster, bigger); -ier handled via JJ/NN
            return "JJR"
        if w.endswith(_NOUN_SUFFIXES):
            return "NN"
        if w.endswith("s") and not w.endswith(("ss", "us", "is")) and len(w) > 3:
            return "NNS"
        return "NN"


class POSTagger(HostTransformer):
    """words -> :class:`TaggedSequence` (reference ``POSTagger.scala:24-35``,
    which wraps an Epic CRF the same way; any object with
    ``best_sequence(words)`` plugs in).

    Default model: the in-tree TRAINED averaged perceptron
    (``perceptron_pos.py``, shipped-artifact held-out 0.9764 token
    accuracy vs the rule-based stand-in's 0.8392) when its shipped
    weights are present; the rule-based model otherwise."""

    def __init__(self, model=None):
        if model is None:
            from .perceptron_pos import load_pretrained

            model = load_pretrained() or RuleBasedPosModel()
        self.model = model

    def apply(self, words: Sequence[str]) -> TaggedSequence:
        return self.model.best_sequence(list(words))


# --------------------------------------------------------------------- NER


@dataclass
class Segmentation:
    """Labeled spans over a word sequence (the Epic ``Segmentation``
    analogue). ``labels[i]`` is the per-token BIO-collapsed label ('O'
    outside any span)."""

    words: List[str]
    spans: List[Tuple[str, int, int]] = field(default_factory=list)

    @property
    def labels(self) -> List[str]:
        out = ["O"] * len(self.words)
        for label, start, end in self.spans:
            for i in range(start, end):
                out[i] = label
        return out


_HONORIFICS = {"mr", "mrs", "ms", "dr", "prof", "sir", "president",
               "senator", "judge", "captain"}
_ORG_SUFFIXES = {"inc", "corp", "ltd", "llc", "co", "company", "university",
                 "institute", "college", "department", "committee", "group",
                 "association", "agency", "bank", "press"}
_LOCATIONS = {
    "america", "europe", "asia", "africa", "australia", "antarctica",
    "usa", "uk", "france", "germany", "china", "japan", "india", "russia",
    "canada", "mexico", "brazil", "italy", "spain", "england", "scotland",
    "london", "paris", "berlin", "tokyo", "beijing", "moscow", "york",
    "boston", "chicago", "seattle", "texas", "california", "washington",
    "berkeley", "stanford",
}
_FIRST_NAMES = {
    "john", "james", "mary", "robert", "michael", "william", "david",
    "richard", "joseph", "thomas", "charles", "sarah", "karen", "nancy",
    "lisa", "betty", "margaret", "sandra", "ashley", "emily", "anna",
    "alice", "bob", "carol", "dave", "eve", "frank", "grace", "henry",
    "jane", "peter", "paul", "george", "susan", "linda", "barbara",
}


class RuleBasedNerModel:
    """Capitalized-span chunker with gazetteer/affix classification:
    PERSON / LOCATION / ORGANIZATION / NUMBER / MISC. The in-tree default
    for :class:`NER`; same protocol as the reference's Epic SemiCRF."""

    def best_sequence(self, words: Sequence[str]) -> Segmentation:
        words = list(words)
        spans: List[Tuple[str, int, int]] = []
        i = 0
        while i < len(words):
            word = words[i]
            if _NUMBER_RE.match(word):
                spans.append(("NUMBER", i, i + 1))
                i += 1
                continue
            if self._capitalized(word) and (i > 0 or self._known(word)):
                j = i
                while j < len(words) and self._capitalized(words[j]):
                    j += 1
                spans.append((self._classify(words[i:j]), i, j))
                i = j
                continue
            i += 1
        return Segmentation(words, spans)

    @staticmethod
    def _capitalized(word: str) -> bool:
        return bool(word) and word[0].isupper() and any(c.isalpha() for c in word)

    @staticmethod
    def _known(word: str) -> bool:
        w = word.lower().rstrip(".")
        return (
            w in _LOCATIONS or w in _FIRST_NAMES or w in _HONORIFICS
            or w in _ORG_SUFFIXES
        )

    @staticmethod
    def _classify(span_words: List[str]) -> str:
        lows = [w.lower().rstrip(".") for w in span_words]
        if lows[-1] in _ORG_SUFFIXES or any(w in _ORG_SUFFIXES for w in lows):
            return "ORGANIZATION"
        if any(w in _LOCATIONS for w in lows):
            return "LOCATION"
        if lows[0] in _HONORIFICS or any(w in _FIRST_NAMES for w in lows):
            return "PERSON"
        return "MISC"


class NER(HostTransformer):
    """words -> :class:`Segmentation` (reference ``NER.scala:20-31``,
    which wraps an Epic SemiCRF the same way; any object with
    ``best_sequence(words)`` plugs in).

    Default model: the in-tree TRAINED averaged perceptron
    (``perceptron_ner.py``, shipped-artifact held-out token F1 1.000 vs
    the rule-based stand-in's 0.9508) when its shipped weights are
    present; the rule-based model otherwise."""

    def __init__(self, model=None):
        if model is None:
            from .perceptron_ner import load_pretrained

            model = load_pretrained() or RuleBasedNerModel()
        self.model = model

    def apply(self, words: Sequence[str]) -> Segmentation:
        return self.model.best_sequence(list(words))


# -------------------------------------------- CoreNLP feature extraction

_SENTENCE_RE = re.compile(r"(?<=[.!?])\s+")
_TOKEN_RE = re.compile(r"[A-Za-z0-9']+|[^\sA-Za-z0-9]")
#: The reference's normalize pattern verbatim ("[^a-zA-Z0-9\\s+]",
#: CoreNLPFeatureExtractor.scala:36): '+' sits INSIDE the negated class
#: there too, so '+' characters survive normalization ("C++" keeps its
#: plusses). Kept bit-for-bit for feature-space parity.
_NORMALIZE_RE = re.compile(r"[^a-zA-Z0-9\s+]")


def _model_key(model):
    """Equality key for a pluggable model: stateless in-tree defaults
    compare by type (so identical pipelines CSE-merge); anything else by
    identity (so differently-configured models never merge)."""
    if type(model) in (RuleBasedPosModel, RuleBasedNerModel):
        return type(model)
    return id(model)


class CoreNLPFeatureExtractor(HostTransformer):
    """string -> lemmatized/entity-typed n-grams (reference
    ``CoreNLPFeatureExtractor.scala:18-38``), in order: tokenize into
    sentences, POS-tag, lemmatize, recognize named entities, replace
    entity tokens with their type ("Paris" -> "LOCATION"), normalize
    (strip non-alphanumerics, lowercase), emit n-grams per sentence for
    each requested order (sentence boundaries are respected, as in the
    reference)."""

    def __init__(self, orders: Sequence[int], pos_model=None, ner_model=None):
        self.orders = list(orders)
        if pos_model is None:
            from .perceptron_pos import load_pretrained as _pos

            pos_model = _pos() or RuleBasedPosModel()
        if ner_model is None:
            from .perceptron_ner import load_pretrained as _ner

            ner_model = _ner() or RuleBasedNerModel()
        self.pos_model = pos_model
        self.ner_model = ner_model

    def eq_key(self):
        return (CoreNLPFeatureExtractor, tuple(self.orders),
                _model_key(self.pos_model), _model_key(self.ner_model))

    def apply(self, text: str) -> List[str]:
        sentences = [
            s for s in _SENTENCE_RE.split(text.strip()) if s
        ]
        token_rows: List[List[str]] = []
        for sent in sentences:
            words = _TOKEN_RE.findall(sent)
            if not words:
                continue
            tagged = self.pos_model.best_sequence(words)
            entities = self.ner_model.best_sequence(words).labels
            if len(tagged.tags) != len(words) or len(entities) != len(words):
                raise ValueError(
                    f"model returned {len(tagged.tags)} tags / "
                    f"{len(entities)} entity labels for {len(words)} words"
                )
            row = []
            for word, tag, entity in zip(words, tagged.tags, entities):
                if entity != "O":
                    row.append(entity)
                else:
                    lemma = english_lemmatize(word, tag)
                    row.append(_NORMALIZE_RE.sub("", lemma).lower())
            token_rows.append([t for t in row if t])
        out: List[str] = []
        for n in self.orders:
            for row in token_rows:
                out.extend(
                    " ".join(row[i : i + n])
                    for i in range(len(row) - n + 1)
                )
        return out
