"""N-gram featurization and counting (reference ``nodes/nlp/ngrams.scala``).

Host-stage nodes. ``NGram`` is a hashable tuple wrapper; counting happens
in one host pass with a dict (the analogue of the reference's
per-partition JHashMap + reduceByKey, ``ngrams.scala:142-185``), then the
sorted (ngram, count) pairs flow on as a host dataset.
"""
from __future__ import annotations

from typing import Any, List, Sequence

from ...parallel.dataset import Dataset, HostDataset
from ...workflow.transformer import HostTransformer


class NGram(tuple):
    """Hashable ngram key (reference ``ngrams.scala:100-133``). A tuple
    subclass: sane equality/hashing for use as dict keys."""

    @property
    def words(self):
        return tuple(self)

    def __repr__(self):
        return "[" + ",".join(str(w) for w in self) + "]"


def _check_orders(orders: Sequence[int]) -> None:
    orders = list(orders)
    assert min(orders) >= 1, f"minimum order is not >= 1, found {min(orders)}"
    for a, b in zip(orders, orders[1:]):
        assert b == a + 1, f"orders are not consecutive; contains {a} and {b}"


class NGramsFeaturizer(HostTransformer):
    """All n-grams of consecutive orders from a token sequence
    (reference ``ngrams.scala:20-91``): for each start position, emit the
    min-order gram then extend one word at a time up to max order."""

    def __init__(self, orders: Sequence[int]):
        _check_orders(orders)
        self.orders = tuple(orders)

    def eq_key(self):
        return (NGramsFeaturizer, self.orders)

    def apply(self, tokens: Sequence[Any]) -> List[NGram]:
        lo, hi = min(self.orders), max(self.orders)
        out: List[NGram] = []
        n = len(tokens)
        for i in range(n - lo + 1):
            for order in range(lo, hi + 1):
                if i + order > n:
                    break
                out.append(NGram(tokens[i : i + order]))
        return out


DEFAULT_MODE = "default"
NO_ADD_MODE = "noAdd"


class NGramsCounts(HostTransformer):
    """Count ngram occurrences over the whole dataset, sorted by frequency
    descending (reference ``ngrams.scala:142-185``). Output is a host
    dataset of (NGram, int) pairs. ``noAdd`` keeps per-item counts without
    global aggregation (the reference's per-partition mode)."""

    def __init__(self, mode: str = DEFAULT_MODE):
        assert mode in (DEFAULT_MODE, NO_ADD_MODE), (
            "`mode` must be `default` or `noAdd`")
        self.mode = mode

    def apply(self, ngrams):  # per-item path is only used by noAdd mode
        counts: dict = {}
        for g in ngrams:
            key = NGram(g)
            counts[key] = counts.get(key, 0) + 1
        return list(counts.items())

    def apply_dataset(self, ds: Dataset) -> Dataset:
        items = ds.collect()
        if self.mode == NO_ADD_MODE:
            return HostDataset([pair for item in items
                                for pair in self.apply(item)])
        counts: dict = {}
        order: dict = {}
        for item in items:
            for g in item:
                key = NGram(g)
                counts[key] = counts.get(key, 0) + 1
                if key not in order:
                    order[key] = len(order)
        # sort by count desc; break ties by first appearance so the
        # ordering is deterministic (the reference's sortBy leaves ties
        # to partition order)
        pairs = sorted(counts.items(), key=lambda kv: (-kv[1], order[kv[0]]))
        return HostDataset(pairs)
