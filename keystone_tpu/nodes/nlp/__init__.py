"""NLP nodes (reference ``nodes/nlp``, SURVEY.md section 2.6).

Text processing is host-stage work (ragged, non-numeric); featurization
hands off to device arrays via sparse vectors (``nodes/util/sparse``).
The reference's CoreNLP/Epic-backed nodes (CoreNLPFeatureExtractor,
POSTagger, NER) keep their node surface here with pluggable models;
small in-tree rule-based English models are the defaults (``corenlp.py``).
"""
from .corenlp import (
    CoreNLPFeatureExtractor,
    NER,
    POSTagger,
    RuleBasedNerModel,
    RuleBasedPosModel,
    Segmentation,
    TaggedSequence,
    english_lemmatize,
)
from .hashing import HashingTF, NGramsHashingTF, java_string_hash, scala_hash
from .indexers import NaiveBitPackIndexer, NGramIndexer, NGramIndexerImpl
from .ngrams import (
    DEFAULT_MODE,
    NO_ADD_MODE,
    NGram,
    NGramsCounts,
    NGramsFeaturizer,
)
from .stupid_backoff import StupidBackoffEstimator, StupidBackoffModel
from .text import LowerCase, Tokenizer, Trim
from .word_freq import OOV_INDEX, WordFrequencyEncoder, WordFrequencyTransformer

__all__ = [
    "CoreNLPFeatureExtractor",
    "NER",
    "POSTagger",
    "RuleBasedNerModel",
    "RuleBasedPosModel",
    "Segmentation",
    "TaggedSequence",
    "english_lemmatize",
    "HashingTF",
    "NGramsHashingTF",
    "java_string_hash",
    "scala_hash",
    "NaiveBitPackIndexer",
    "NGramIndexer",
    "NGramIndexerImpl",
    "NGram",
    "NGramsCounts",
    "NGramsFeaturizer",
    "DEFAULT_MODE",
    "NO_ADD_MODE",
    "StupidBackoffEstimator",
    "StupidBackoffModel",
    "LowerCase",
    "Tokenizer",
    "Trim",
    "WordFrequencyEncoder",
    "WordFrequencyTransformer",
    "OOV_INDEX",
]
