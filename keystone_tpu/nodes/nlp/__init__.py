"""NLP nodes (reference ``nodes/nlp``, SURVEY.md section 2.6).

Text processing is host-stage work (ragged, non-numeric); featurization
hands off to device arrays via sparse vectors (``nodes/util/sparse``).
The reference's CoreNLP/Epic-backed nodes (CoreNLPFeatureExtractor,
POSTagger, NER) wrap external JVM model libraries with no TPU analogue;
they are intentionally out of scope here and their pipeline role
(lemmatized-ngram extraction) is covered by Tokenizer + NGramsFeaturizer.
"""
from .hashing import HashingTF, NGramsHashingTF, java_string_hash, scala_hash
from .indexers import NaiveBitPackIndexer, NGramIndexer, NGramIndexerImpl
from .ngrams import (
    DEFAULT_MODE,
    NO_ADD_MODE,
    NGram,
    NGramsCounts,
    NGramsFeaturizer,
)
from .stupid_backoff import StupidBackoffEstimator, StupidBackoffModel
from .text import LowerCase, Tokenizer, Trim
from .word_freq import OOV_INDEX, WordFrequencyEncoder, WordFrequencyTransformer

__all__ = [
    "HashingTF",
    "NGramsHashingTF",
    "java_string_hash",
    "scala_hash",
    "NaiveBitPackIndexer",
    "NGramIndexer",
    "NGramIndexerImpl",
    "NGram",
    "NGramsCounts",
    "NGramsFeaturizer",
    "DEFAULT_MODE",
    "NO_ADD_MODE",
    "StupidBackoffEstimator",
    "StupidBackoffModel",
    "LowerCase",
    "Tokenizer",
    "Trim",
    "WordFrequencyEncoder",
    "WordFrequencyTransformer",
    "OOV_INDEX",
]
