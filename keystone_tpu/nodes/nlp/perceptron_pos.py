"""Averaged-perceptron POS tagger — the in-tree TRAINED statistical
model closing the POS leg of the reference's Epic CRF gap (reference
``nodes/nlp/POSTagger.scala:24-35`` wraps ``epic.models.PosTagSelector``;
VERDICT r3 next#9 asked for a dependency-free statistical tagger that
beats the rule-based stand-in's 0.839 token accuracy).

Model: greedy left-to-right decoding over history features (previous
tag, previous tag pair) with averaged-perceptron training — the
standard strong baseline for feature-rich sequence tagging. Features
are word identity, affixes, orthographic shape, and a +-2 word window;
weights are a plain dict-of-dicts serialized as gzip JSON, so training
and inference need nothing beyond the standard library.

Shipped weights: ``data/pos_perceptron.json.gz``, trained by
``tools/train_pos.py`` on the in-tree hand-tagged corpus
(``tests/resources/pos_train_corpus.txt``, 328 sentences authored for
this purpose) and evaluated on the held-out gold sample
(``tests/resources/pos_tagged_sample.txt``) — the train/eval split is
by-file with deliberately divergent vocabulary, so the shipped accuracy
measures generalization. ``tests/test_nlp_quality.py`` pins the floor.
"""
from __future__ import annotations

import gzip
import json
import os
import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from .corenlp import TaggedSequence

_DATA_PATH = os.path.join(os.path.dirname(__file__), "data",
                          "pos_perceptron.json.gz")


def _shape(word: str) -> str:
    """Collapsed orthographic shape: 'Xxx', 'dd', 'x-x', ..."""
    out = []
    for ch in word[:8]:
        if ch.isupper():
            tok = "X"
        elif ch.islower():
            tok = "x"
        elif ch.isdigit():
            tok = "d"
        else:
            tok = ch
        if not out or out[-1] != tok:
            out.append(tok)
    return "".join(out)


#: The rule-based tagger doubles as a feature generator: its lexicon +
#: suffix + shape guess (0.839 on the gold sample by itself) enters the
#: perceptron as a stacked prior the training can trust, override, or
#: condition on — the standard model-stacking trick for small corpora.
_RULE_MODEL = None


def _rule_guess(word: str, sentence_initial: bool) -> str:
    global _RULE_MODEL
    if _RULE_MODEL is None:
        from .corenlp import RuleBasedPosModel

        _RULE_MODEL = RuleBasedPosModel()
    return _RULE_MODEL._tag(word, sentence_initial=sentence_initial)


def _features(words: Sequence[str], i: int, prev: str, prev2: str):
    """Feature strings for position i given decoded history. Mirrors the
    classic averaged-perceptron tagger feature set (word window,
    affixes, shape, tag history) plus the stacked rule-based guess."""
    w = words[i]
    lw = w.lower()
    prior = words[i - 1].lower() if i > 0 else "<s>"
    prior2 = words[i - 2].lower() if i > 1 else "<s>"
    nxt = words[i + 1].lower() if i + 1 < len(words) else "</s>"
    nxt2 = words[i + 2].lower() if i + 2 < len(words) else "</s>"
    feats = [
        "b",                      # bias
        "w=" + lw,
        "suf3=" + lw[-3:],
        "suf2=" + lw[-2:],
        "suf1=" + lw[-1:],
        "pre1=" + lw[:1],
        "shape=" + _shape(w),
        "t-1=" + prev,
        "t-2t-1=" + prev2 + "|" + prev,
        "w-1=" + prior,
        "w-2=" + prior2,
        "w+1=" + nxt,
        "w+2=" + nxt2,
        "t-1w=" + prev + "|" + lw,
        "first" if i == 0 else "mid",
        "rule=" + _rule_guess(w, i == 0),
        "rule,t-1=" + _rule_guess(w, i == 0) + "|" + prev,
    ]
    if any(c.isdigit() for c in w):
        feats.append("hasdigit")
    if "-" in w:
        feats.append("hyphen")
    if w[:1].isupper():
        feats.append("cap")
        if i > 0:
            feats.append("cap-mid")
    return feats


class AveragedPerceptronPosModel:
    """``best_sequence(words)`` protocol-compatible with
    :class:`~keystone_tpu.nodes.nlp.corenlp.RuleBasedPosModel` (and so
    with the reference's Epic CRF wrapper)."""

    def __init__(self, weights: Optional[Dict[str, Dict[str, float]]] = None,
                 tags: Optional[List[str]] = None):
        # weights: feature -> {tag -> weight}
        self.weights = weights or {}
        self.tags = tags or []

    # -- inference --------------------------------------------------------
    def _score_tag(self, feats) -> str:
        scores = defaultdict(float)
        for f in feats:
            wf = self.weights.get(f)
            if not wf:
                continue
            for tag, weight in wf.items():
                scores[tag] += weight
        if not scores:
            return "NN"
        # deterministic tie-break on the tag name
        return max(self.tags, key=lambda t: (scores[t], t)) if self.tags \
            else max(sorted(scores), key=scores.get)

    def best_sequence(self, words: Sequence[str]) -> TaggedSequence:
        prev, prev2 = "<s>", "<s>"
        tags: List[str] = []
        for i in range(len(words)):
            tag = self._score_tag(_features(words, i, prev, prev2))
            tags.append(tag)
            prev2, prev = prev, tag
        return TaggedSequence(list(words), tags)

    # -- training ---------------------------------------------------------
    @classmethod
    def train(cls, sentences: Sequence[List[Tuple[str, str]]],
              epochs: int = 8, seed: int = 0) -> "AveragedPerceptronPosModel":
        """Averaged-perceptron training on (word, tag) sentences —
        greedy decoding against gold history, accumulate-and-average to
        resist overfitting on small corpora."""
        rng = random.Random(seed)
        tags = sorted({t for sent in sentences for _, t in sent})
        model = cls(weights={}, tags=tags)
        totals: Dict[Tuple[str, str], float] = defaultdict(float)
        stamps: Dict[Tuple[str, str], int] = defaultdict(int)
        step = 0

        def upd(feat, tag, delta):
            nonlocal step
            key = (feat, tag)
            cur = model.weights.setdefault(feat, {}).get(tag, 0.0)
            totals[key] += (step - stamps[key]) * cur
            stamps[key] = step
            model.weights[feat][tag] = cur + delta

        data = list(sentences)
        for _ in range(epochs):
            rng.shuffle(data)
            for sent in data:
                words = [w for w, _ in sent]
                prev, prev2 = "<s>", "<s>"
                for i, (_, gold) in enumerate(sent):
                    feats = _features(words, i, prev, prev2)
                    guess = model._score_tag(feats)
                    step += 1
                    if guess != gold:
                        for f in feats:
                            upd(f, gold, +1.0)
                            upd(f, guess, -1.0)
                    # decoded history: training sees the same noisy
                    # tag context inference will (no exposure bias)
                    prev2, prev = prev, guess
        # average
        for feat, per_tag in model.weights.items():
            for tag, cur in per_tag.items():
                key = (feat, tag)
                total = totals[key] + (step - stamps[key]) * cur
                per_tag[tag] = round(total / step, 5)
        # prune zeros (smaller artifact)
        model.weights = {
            f: {t: w for t, w in per.items() if w}
            for f, per in model.weights.items()
        }
        model.weights = {f: per for f, per in model.weights.items() if per}
        return model

    # -- persistence ------------------------------------------------------
    def save(self, path: str = _DATA_PATH) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with gzip.open(path, "wt") as f:
            json.dump({"tags": self.tags, "weights": self.weights}, f)

    @classmethod
    def load(cls, path: str = _DATA_PATH) -> "AveragedPerceptronPosModel":
        with gzip.open(path, "rt") as f:
            blob = json.load(f)
        return cls(weights=blob["weights"], tags=blob["tags"])


_PRETRAINED_CACHE: List[Optional[AveragedPerceptronPosModel]] = []


def load_pretrained() -> Optional[AveragedPerceptronPosModel]:
    """The shipped trained model (process-wide singleton, so identical
    default pipelines CSE-merge on model identity), or None when the
    artifact is absent (callers fall back to the rule-based model)."""
    if not _PRETRAINED_CACHE:
        _PRETRAINED_CACHE.append(
            AveragedPerceptronPosModel.load()
            if os.path.exists(_DATA_PATH) else None)
    return _PRETRAINED_CACHE[0]


def read_tagged_file(path: str) -> List[List[Tuple[str, str]]]:
    """word_TAG lines -> [(word, tag)] sentences (comments skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            out.append([tuple(tok.rsplit("_", 1)) for tok in line.split()])
    return out
