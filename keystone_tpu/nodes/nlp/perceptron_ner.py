"""Averaged-perceptron NER — the in-tree TRAINED statistical model
closing the NER leg of the reference's Epic SemiCRF gap (reference
``nodes/nlp/NER.scala:20-31`` wraps ``epic.models.NerSelector``;
VERDICT r4 next#5 asked for the POS recipe applied to NER: train over
an in-tree authored corpus, beat the rule-based stand-in's held-out
F1, wire as the default).

Model: greedy left-to-right token-level labeling (PERSON / LOCATION /
ORGANIZATION / NUMBER / O) over history features, averaged-perceptron
training — the same dependency-free recipe as ``perceptron_pos.py``.
The rule-based NER enters as a stacked prior (its gazetteer + affix
label is a feature the training can trust, override, or condition on),
so the perceptron starts from the rule model's knowledge and learns
contextual corrections the rules cannot express (e.g. "studied at
Berkeley" -> ORGANIZATION even though the gazetteer says LOCATION).
Adjacent same-label tokens merge into spans for the
:class:`~keystone_tpu.nodes.nlp.corenlp.Segmentation` output.

Shipped weights: ``data/ner_perceptron.json.gz``, trained by
``tools/train_ner.py`` on the in-tree hand-labeled corpus
(``tests/resources/ner_train_corpus.txt``, 200 sentences authored for
this purpose) and evaluated on the held-out gold sample
(``tests/resources/ner_tagged_sample.txt``) — entity vocabulary in the
two files deliberately diverges, so the shipped F1 measures
generalization. ``tests/test_nlp_quality.py`` pins the floor.

Known limitation (ADVICE r5 low#4): ``best_sequence`` merges ALL
adjacent same-label tokens into one span, so two distinct adjacent
entities of the same type ("... Alice Bob ..." as two people, or two
back-to-back organization names) coalesce into a single span — unlike
the reference's Epic SemiCRF, whose segmentation model can place a
boundary between them. Token-level consumers are unaffected
(``label_sequence`` / ``Segmentation.labels`` are exact); only
span-level consumers see merged entities. Recovering boundaries would
require BIO-style labels in training and decoding; the current
token-level behavior is pinned by a regression test in
``tests/test_nlp_quality.py``.
"""
from __future__ import annotations

import gzip
import json
import os
import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from .corenlp import Segmentation
from .perceptron_pos import _shape

_DATA_PATH = os.path.join(os.path.dirname(__file__), "data",
                          "ner_perceptron.json.gz")

_RULE_MODEL = None


def _rule_labels(words: Sequence[str]) -> List[str]:
    global _RULE_MODEL
    if _RULE_MODEL is None:
        from .corenlp import RuleBasedNerModel

        _RULE_MODEL = RuleBasedNerModel()
    return _RULE_MODEL.best_sequence(list(words)).labels


def _features(words: Sequence[str], rule: Sequence[str], i: int,
              prev: str, prev2: str) -> List[str]:
    """Feature strings for position i given decoded label history."""
    w = words[i]
    lw = w.lower()
    prior = words[i - 1] if i > 0 else "<s>"
    prior2 = words[i - 2] if i > 1 else "<s>"
    nxt = words[i + 1] if i + 1 < len(words) else "</s>"
    nxt2 = words[i + 2] if i + 2 < len(words) else "</s>"
    feats = [
        "b",
        "w=" + lw,
        "suf3=" + lw[-3:],
        "pre3=" + lw[:3],
        "shape=" + _shape(w),
        "l-1=" + prev,
        "l-2l-1=" + prev2 + "|" + prev,
        "w-1=" + prior.lower(),
        "w-2=" + prior2.lower(),
        "w+1=" + nxt.lower(),
        "w+2=" + nxt2.lower(),
        "l-1w=" + prev + "|" + lw,
        "w-1w=" + prior.lower() + "|" + lw,
        "first" if i == 0 else "mid",
        "rule=" + rule[i],
        "rule,l-1=" + rule[i] + "|" + prev,
        "rule,w-1=" + rule[i] + "|" + prior.lower(),
    ]
    if w[:1].isupper():
        feats.append("cap")
        if i > 0:
            feats.append("cap-mid")
        if nxt[:1].isupper():
            feats.append("cap-next-cap")
        if prior[:1].isupper() and i > 0:
            feats.append("cap-prev-cap")
    if w.isupper() and len(w) > 1:
        feats.append("allcaps")
    if any(c.isdigit() for c in w):
        feats.append("hasdigit")
    if w.isdigit():
        feats.append("alldigit")
    return feats


class AveragedPerceptronNerModel:
    """``best_sequence(words) -> Segmentation`` — protocol-compatible
    with :class:`~keystone_tpu.nodes.nlp.corenlp.RuleBasedNerModel`
    (and so with the reference's Epic SemiCRF wrapper)."""

    def __init__(self, weights: Optional[Dict[str, Dict[str, float]]] = None,
                 labels: Optional[List[str]] = None):
        self.weights = weights or {}
        self.labels = labels or []

    # -- inference --------------------------------------------------------
    def _score_label(self, feats) -> str:
        scores = defaultdict(float)
        for f in feats:
            wf = self.weights.get(f)
            if not wf:
                continue
            for lab, weight in wf.items():
                scores[lab] += weight
        if not scores:
            return "O"
        return max(self.labels, key=lambda t: (scores[t], t)) if self.labels \
            else max(sorted(scores), key=scores.get)

    def label_sequence(self, words: Sequence[str]) -> List[str]:
        rule = _rule_labels(words)
        prev, prev2 = "<s>", "<s>"
        out: List[str] = []
        for i in range(len(words)):
            lab = self._score_label(_features(words, rule, i, prev, prev2))
            out.append(lab)
            prev2, prev = prev, lab
        return out

    def best_sequence(self, words: Sequence[str]) -> Segmentation:
        words = list(words)
        labels = self.label_sequence(words)
        spans: List[Tuple[str, int, int]] = []
        i = 0
        while i < len(words):
            if labels[i] == "O":
                i += 1
                continue
            j = i
            while j < len(words) and labels[j] == labels[i]:
                j += 1
            spans.append((labels[i], i, j))
            i = j
        return Segmentation(words, spans)

    # -- training ---------------------------------------------------------
    @classmethod
    def train(cls, sentences: Sequence[List[Tuple[str, str]]],
              epochs: int = 8, seed: int = 0) -> "AveragedPerceptronNerModel":
        """Averaged-perceptron training on (word, label) sentences with
        decoded history (same accumulate-and-average scheme as
        ``AveragedPerceptronPosModel.train``)."""
        rng = random.Random(seed)
        labels = sorted({lab for sent in sentences for _, lab in sent})
        model = cls(weights={}, labels=labels)
        totals: Dict[Tuple[str, str], float] = defaultdict(float)
        stamps: Dict[Tuple[str, str], int] = defaultdict(int)
        step = 0

        def upd(feat, lab, delta):
            key = (feat, lab)
            cur = model.weights.setdefault(feat, {}).get(lab, 0.0)
            totals[key] += (step - stamps[key]) * cur
            stamps[key] = step
            model.weights[feat][lab] = cur + delta

        data = list(sentences)
        for _ in range(epochs):
            rng.shuffle(data)
            for sent in data:
                words = [w for w, _ in sent]
                rule = _rule_labels(words)
                prev, prev2 = "<s>", "<s>"
                for i, (_, gold) in enumerate(sent):
                    feats = _features(words, rule, i, prev, prev2)
                    guess = model._score_label(feats)
                    step += 1
                    if guess != gold:
                        for f in feats:
                            upd(f, gold, +1.0)
                            upd(f, guess, -1.0)
                    prev2, prev = prev, guess
        for feat, per in model.weights.items():
            for lab, cur in per.items():
                key = (feat, lab)
                total = totals[key] + (step - stamps[key]) * cur
                per[lab] = round(total / step, 5)
        model.weights = {
            f: {t: w for t, w in per.items() if w}
            for f, per in model.weights.items()
        }
        model.weights = {f: per for f, per in model.weights.items() if per}
        return model

    # -- persistence ------------------------------------------------------
    def save(self, path: str = _DATA_PATH) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with gzip.open(path, "wt") as f:
            json.dump({"labels": self.labels, "weights": self.weights}, f)

    @classmethod
    def load(cls, path: str = _DATA_PATH) -> "AveragedPerceptronNerModel":
        with gzip.open(path, "rt") as f:
            blob = json.load(f)
        return cls(weights=blob["weights"], labels=blob["labels"])


_PRETRAINED_CACHE: List[Optional[AveragedPerceptronNerModel]] = []


def load_pretrained() -> Optional[AveragedPerceptronNerModel]:
    """The shipped trained model (process-wide singleton, so identical
    default pipelines CSE-merge on model identity), or None when the
    artifact is absent (callers fall back to the rule-based model)."""
    if not _PRETRAINED_CACHE:
        _PRETRAINED_CACHE.append(
            AveragedPerceptronNerModel.load()
            if os.path.exists(_DATA_PATH) else None)
    return _PRETRAINED_CACHE[0]


def read_labeled_file(path: str) -> List[List[Tuple[str, str]]]:
    """word|LABEL lines -> [(word, label)] sentences (comments skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            out.append([tuple(tok.split("|")) for tok in line.split()])
    return out
