"""String preprocessing nodes (reference ``nodes/nlp/StringUtils.scala``).

These are host-stage nodes: tokenization is ragged, non-numeric work that
belongs on the host CPU side of the DAG (SURVEY.md section 7 "Host/device
choreography for NLP"). Downstream featurization (hashing TF, sparse
vectorization) turns their output into device arrays.
"""
from __future__ import annotations

import re

from ...workflow.transformer import HostTransformer


class Tokenizer(HostTransformer):
    """Split a string into tokens on a delimiter regex
    (reference ``StringUtils.scala:13-15``; default splits on punctuation
    and whitespace, dropping empty leading fields like Scala's split)."""

    def __init__(self, sep: str = r"[\W_\s]+"):
        self.sep = sep
        self._re = re.compile(sep)

    def eq_key(self):
        return (Tokenizer, self.sep)

    def apply(self, s: str):
        parts = self._re.split(s)
        # JVM String.split semantics: trailing empty fields are removed,
        # leading/interior ones are kept.
        while parts and parts[-1] == "":
            parts.pop()
        return parts

    def __getstate__(self):
        state = super().__getstate__()
        state.pop("_re", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._re = re.compile(self.sep)


class Trim(HostTransformer):
    """Strip leading/trailing whitespace (``StringUtils.scala:20-22``)."""

    def apply(self, s: str) -> str:
        return s.strip()


class LowerCase(HostTransformer):
    """Lower-case a string (``StringUtils.scala:28-30``)."""

    def apply(self, s: str) -> str:
        return s.lower()
