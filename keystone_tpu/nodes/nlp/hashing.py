"""Feature hashing (reference ``nodes/nlp/HashingTF.scala`` and
``nodes/nlp/NGramsHashingTF.scala``).

Python's builtin ``hash`` is salted per process, so feature indices would
not be reproducible across runs. Instead we implement the reference's
exact hash family: JVM ``String.hashCode`` for terms and MurmurHash3
ordered ("Seq") hashing for ngram tuples — so ``NGramsHashingTF`` is
bit-identical to ``NGramsFeaturizer`` followed by ``HashingTF``, the same
equivalence the reference guarantees (``NGramsHashingTF.scala:14-17``).

Output is a host :class:`~keystone_tpu.nodes.util.sparse.SparseVector`;
batches densify or CSR-pack on device downstream.
"""
from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ...workflow.transformer import HostTransformer
from ..util.sparse import SparseVector
from .ngrams import _check_orders

_MASK = 0xFFFFFFFF


def _to_signed(x: int) -> int:
    x &= _MASK
    return x - (1 << 32) if x >= (1 << 31) else x


def java_string_hash(s: str) -> int:
    """JVM ``String.hashCode``: h = 31*h + c over UTF-16 code units."""
    h = 0
    data = s.encode("utf-16-be")
    for i in range(0, len(data), 2):
        unit = (data[i] << 8) | data[i + 1]
        h = (31 * h + unit) & _MASK
    return _to_signed(h)


def _rotl(x: int, r: int) -> int:
    x &= _MASK
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur_mix(h: int, k: int) -> int:
    """MurmurHash3 mix step (reference ``NGramsHashingTF.scala:41-46``)."""
    h = murmur_mix_last(h, k)
    h = _rotl(h, 13)
    return (h * 5 + 0xE6546B64) & _MASK


def murmur_mix_last(h: int, k: int) -> int:
    k = (k * 0xCC9E2D51) & _MASK
    k = _rotl(k, 15)
    k = (k * 0x1B873593) & _MASK
    return (h ^ k) & _MASK


def murmur_finalize(h: int, length: int) -> int:
    h = (h ^ length) & _MASK
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return _to_signed(h)


SEQ_SEED = java_string_hash("Seq")


def scala_hash(term: Any) -> int:
    """Scala's ``.##`` for the term types that appear in pipelines:
    strings (String.hashCode), ints (identity), and ngram sequences
    (MurmurHash3 ordered hash with the "Seq" seed)."""
    if isinstance(term, str):
        return java_string_hash(term)
    if isinstance(term, (int, np.integer)):
        return _to_signed(int(term))
    if isinstance(term, (tuple, list)):
        h = SEQ_SEED & _MASK
        for w in term:
            h = murmur_mix(h, scala_hash(w) & _MASK)
        return murmur_finalize(h, len(term))
    raise TypeError(f"unhashable term type for feature hashing: {type(term)}")


def non_negative_mod(x: int, mod: int) -> int:
    r = x % mod  # Python % is already non-negative for positive mod
    return r


class HashingTF(HostTransformer):
    """Term sequence -> sparse term-frequency vector via the hashing trick
    (reference ``HashingTF.scala:15-30``)."""

    def __init__(self, num_features: int):
        self.num_features = int(num_features)

    def eq_key(self):
        return (HashingTF, self.num_features)

    def apply(self, document: Sequence[Any]) -> SparseVector:
        tf: dict = {}
        for term in document:
            i = non_negative_mod(scala_hash(term), self.num_features)
            tf[i] = tf.get(i, 0.0) + 1.0
        return SparseVector.from_dict(tf, self.num_features)


class NGramsHashingTF(HostTransformer):
    """Rolling-hash fused NGramsFeaturizer + HashingTF
    (reference ``NGramsHashingTF.scala:26-118``): per start position, mix
    one term hash at a time, emitting a finalized feature index at every
    order — identical output, no ngram materialization."""

    def __init__(self, orders: Sequence[int], num_features: int):
        _check_orders(orders)
        self.orders = tuple(orders)
        self.num_features = int(num_features)

    def eq_key(self):
        return (NGramsHashingTF, self.orders, self.num_features)

    def apply(self, line: Sequence[str]) -> SparseVector:
        from ...native import available, ngram_hash_features

        if available():
            feats = ngram_hash_features(
                list(line), self.orders, self.num_features)
            idx, counts = np.unique(feats, return_counts=True)
            return SparseVector(idx, counts.astype(np.float32),
                                self.num_features)
        lo, hi = min(self.orders), max(self.orders)
        hashes = [scala_hash(t) & _MASK for t in line]
        n = len(line)
        tf: dict = {}
        for i in range(n - lo + 1):
            h = SEQ_SEED & _MASK
            for j in range(i, i + lo):
                h = murmur_mix(h, hashes[j])
            feat = non_negative_mod(murmur_finalize(h, lo), self.num_features)
            tf[feat] = tf.get(feat, 0.0) + 1.0
            for order in range(lo + 1, hi + 1):
                if i + order > n:
                    break
                h = murmur_mix(h, hashes[i + order - 1])
                feat = non_negative_mod(
                    murmur_finalize(h, order), self.num_features)
                tf[feat] = tf.get(feat, 0.0) + 1.0
        return SparseVector.from_dict(tf, self.num_features)
