"""Stupid Backoff language model (reference ``nodes/nlp/StupidBackoff.scala``;
Brants et al., "Large language models in machine translation", 2007).

Scores are relative frequencies with multiplicative ``alpha`` backoff:

    S(w_i | context) = freq(ngram) / freq(context)    if freq(ngram) > 0
                       alpha * S(w_i | shorter ctx)   otherwise
    S(w_i)           = freq(w_i) / N

Fit aggregates the (ngram, count) pairs into a hash map and pre-scores
every seen ngram — the analogue of the reference's
InitialBigramPartitioner + per-partition scoring
(``StupidBackoff.scala:152-176``), collapsed to one host pass; the
grouping-by-initial-bigram is a Spark shuffle artifact with no TPU
equivalent needed.
"""
from __future__ import annotations

from typing import Dict, Iterable, Tuple

from ...parallel.dataset import Dataset, HostDataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import HostTransformer
from .indexers import NGramIndexerImpl
from .ngrams import NGram


class StupidBackoffModel(HostTransformer):
    """Query with ``score(ngram)`` (reference ``StupidBackoff.scala:98-128``)."""

    def __init__(
        self,
        scores: Dict[NGram, float],
        ngram_counts: Dict[NGram, int],
        unigram_counts: Dict[object, int],
        num_tokens: int,
        alpha: float = 0.4,
    ):
        self.scores = scores
        self.ngram_counts = ngram_counts
        self.unigram_counts = unigram_counts
        self.num_tokens = int(num_tokens)
        self.alpha = float(alpha)
        self._indexer = NGramIndexerImpl()

    def eq_key(self):
        return (StupidBackoffModel, id(self.scores))

    def score(self, ngram: NGram) -> float:
        ngram = NGram(ngram)
        cached = self.scores.get(ngram)
        if cached is not None:
            return cached
        return self._score(1.0, ngram, self.ngram_counts.get(ngram, 0))

    def _score(self, accum: float, ngram: NGram, freq: int) -> float:
        """Recursive local scoring (reference ``StupidBackoff.scala:62-92``)."""
        idx = self._indexer
        order = idx.ngram_order(ngram)
        if order == 1:
            return accum * self.unigram_counts.get(ngram[0], 0) / self.num_tokens
        if freq != 0:
            context = idx.remove_current_word(ngram)
            if order != 2:
                context_freq = self.ngram_counts.get(context, 0)
            else:
                context_freq = self.unigram_counts.get(context[0], 0)
            if context_freq > 0:
                return accum * freq / context_freq
            # context unseen (e.g. counts fitted without order-1 grams):
            # fall through to backoff instead of dividing by zero
        backed = idx.remove_farthest_word(ngram)
        if order != 2:
            freq2 = self.ngram_counts.get(backed, 0)
        else:
            freq2 = self.unigram_counts.get(backed[0], 0)
        return self._score(self.alpha * accum, backed, freq2)

    def apply(self, pair: Tuple[NGram, int]) -> Tuple[NGram, float]:
        ngram, _ = pair
        return NGram(ngram), self.score(NGram(ngram))


class StupidBackoffEstimator(Estimator):
    """Fit from a dataset of (ngram, count) pairs
    (reference ``StupidBackoff.scala:143-182``)."""

    def __init__(self, unigram_counts: Dict[object, int], alpha: float = 0.4):
        self.unigram_counts = dict(unigram_counts)
        self.alpha = float(alpha)

    def eq_key(self):
        return (StupidBackoffEstimator, id(self.unigram_counts), self.alpha)

    def _fit(self, ds: Dataset) -> StupidBackoffModel:
        counts: Dict[NGram, int] = {}
        for ngram, c in ds.collect():
            key = NGram(ngram)
            counts[key] = counts.get(key, 0) + int(c)
        num_tokens = sum(self.unigram_counts.values())
        model = StupidBackoffModel(
            {}, counts, self.unigram_counts, num_tokens, self.alpha)
        scores: Dict[NGram, float] = {}
        for ngram, freq in counts.items():
            s = model._score(1.0, ngram, freq)
            assert 0.0 <= s <= 1.0, f"score {s} not in [0,1] for {ngram}"
            scores[ngram] = s
        model.scores = scores
        return model
