"""Word frequency encoding (reference ``nodes/nlp/WordFrequencyEncoder.scala``).

Tokens are mapped to their index in sorted-by-frequency order (most
frequent word = 0); out-of-vocabulary words map to -1. Fit counts
unigrams in one host pass (the reference builds them with
NGramsFeaturizer(1..1) + NGramsCounts and collects to the driver).
"""
from __future__ import annotations

from typing import Dict, List, Sequence

from ...parallel.dataset import Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import HostTransformer

OOV_INDEX = -1


class WordFrequencyTransformer(HostTransformer):
    """token seq -> frequency-rank int seq
    (reference ``WordFrequencyEncoder.scala:42-62``)."""

    def __init__(self, word_index: Dict[str, int], unigram_counts: Dict[int, int]):
        self.word_index = dict(word_index)
        self.unigram_counts = dict(unigram_counts)

    def eq_key(self):
        return (WordFrequencyTransformer, id(self.word_index))

    def apply(self, words: Sequence[str]) -> List[int]:
        index = self.word_index
        return [index.get(w, OOV_INDEX) for w in words]


class WordFrequencyEncoder(Estimator):
    """Fit a WordFrequencyTransformer by counting unigrams
    (reference ``WordFrequencyEncoder.scala:12-30``); rank order is count
    descending with ties broken by first appearance."""

    def _fit(self, ds: Dataset) -> WordFrequencyTransformer:
        counts: Dict[str, int] = {}
        first: Dict[str, int] = {}
        i = 0
        for tokens in ds.collect():
            for w in tokens:
                counts[w] = counts.get(w, 0) + 1
                if w not in first:
                    first[w] = i
                i += 1
        ranked = sorted(counts, key=lambda w: (-counts[w], first[w]))
        word_index = {w: r for r, w in enumerate(ranked)}
        unigrams = {word_index[w]: c for w, c in counts.items()}
        return WordFrequencyTransformer(word_index, unigrams)
