"""N-gram indexers (reference ``nodes/nlp/indexers.scala``).

``pack``/``unpack`` utilities for language models needing backoff
contexts. ``NaiveBitPackIndexer`` packs up to trigrams of word ids
(< 2**20) into one int64 — the layout the reference documents at
``indexers.scala:47-58`` — making ngram keys fixed-width integers that
can live in device arrays.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from .ngrams import NGram

_WORD_BITS = 20
_WORD_MASK = (1 << _WORD_BITS) - 1


class NGramIndexer:
    min_ngram_order = 1
    max_ngram_order = 5

    def pack(self, ngram: Sequence) -> NGram:
        raise NotImplementedError


class NGramIndexerImpl(NGramIndexer):
    """Tuple-backed indexer (reference ``indexers.scala:120-135``)."""

    def pack(self, ngram: Sequence) -> NGram:
        return NGram(ngram)

    def unpack(self, ngram: NGram, pos: int):
        return ngram[pos]

    def remove_farthest_word(self, ngram: NGram) -> NGram:
        return NGram(ngram[1:])

    def remove_current_word(self, ngram: NGram) -> NGram:
        return NGram(ngram[:-1])

    def ngram_order(self, ngram: NGram) -> int:
        return len(ngram)


class NaiveBitPackIndexer(NGramIndexer):
    """Bit-packs up to 3 word ids into an int64: 4 control bits (order-1),
    then words left-aligned farthest-first (reference
    ``indexers.scala:60-118``)."""

    min_ngram_order = 1
    max_ngram_order = 3

    def pack(self, ngram: Sequence[int]) -> int:
        for w in ngram:
            assert 0 <= w < (1 << _WORD_BITS), f"word id {w} >= 2**20"
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[0] << 40) | (ngram[1] << 20) | (1 << 60)
        if n == 3:
            return (ngram[0] << 40) | (ngram[1] << 20) | ngram[2] | (1 << 61)
        raise ValueError("ngram order must be in {1, 2, 3}")

    def unpack(self, packed: int, pos: int) -> int:
        if pos == 0:
            return (packed >> 40) & _WORD_MASK
        if pos == 1:
            return (packed >> 20) & _WORD_MASK
        if pos == 2:
            return packed & _WORD_MASK
        raise ValueError("pos must be in {0, 1, 2}")

    def ngram_order(self, packed: int) -> int:
        order = (packed >> 60) & 0xF
        assert 0 <= order <= 2, f"invalid control bits {order}"
        return order + 1

    def remove_farthest_word(self, packed: int) -> int:
        order = self.ngram_order(packed)
        words = [self.unpack(packed, i) for i in range(order)]
        if order == 2:
            return self.pack(words[1:])
        if order == 3:
            return self.pack(words[1:])
        raise ValueError(f"ngram order {order} not supported")

    def remove_current_word(self, packed: int) -> int:
        order = self.ngram_order(packed)
        words = [self.unpack(packed, i) for i in range(order)]
        if order in (2, 3):
            return self.pack(words[:-1])
        raise ValueError(f"ngram order {order} not supported")
