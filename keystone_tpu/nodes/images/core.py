"""Image pipeline nodes.

TPU-native re-designs of the reference's ``nodes/images`` package
(SURVEY.md section 2.4). Images are (H, W, C) float arrays; batch
execution vmaps/convolves over the sharded batch.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import image_ops
from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.transformer import Transformer


class ImageVectorizer(Transformer):
    """Flatten an image to a vector (reference ``images/ImageVectorizer``)."""

    def apply(self, img):
        return img.reshape(-1)


class PixelScaler(Transformer):
    """Divide pixels by 255 (reference ``images/PixelScaler``)."""

    def apply(self, img):
        return img / 255.0


class GrayScaler(Transformer):
    """MATLAB-weight grayscale (reference ``images/GrayScaler``)."""

    def apply(self, img):
        return image_ops.to_grayscale(img)


class Cropper(Transformer):
    """Static crop [x0:x1, y0:y1] (reference ``images/Cropper``)."""

    def __init__(self, x0: int, y0: int, x1: int, y1: int):
        self.x0, self.y0, self.x1, self.y1 = x0, y0, x1, y1

    def apply(self, img):
        return img[self.x0 : self.x1, self.y0 : self.y1, :]


class SymmetricRectifier(Transformer):
    """Channel-doubling rectifier [max(v, x-a), max(v, -x-a)]
    (reference ``images/SymmetricRectifier.scala:12-30``)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = float(max_val)
        self.alpha = float(alpha)

    def apply(self, img):
        pos = jnp.maximum(self.max_val, img - self.alpha)
        neg = jnp.maximum(self.max_val, -img - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)


class Pooler(Transformer):
    """Strided spatial pooling (reference ``images/Pooler.scala:20-68``).
    pixel_fn/pool_fn are named ('identity'|'abs'|'square',
    'sum'|'max'|'mean') so node equality stays structural."""

    def __init__(
        self,
        stride: int,
        pool_size: int,
        pixel_fn: str = "identity",
        pool_fn: str = "sum",
    ):
        self.stride = stride
        self.pool_size = pool_size
        self.pixel_fn = pixel_fn
        self.pool_fn = pool_fn

    def apply(self, img):
        return image_ops.pool_image(
            img, self.stride, self.pool_size, self.pixel_fn, self.pool_fn
        )


class Convolver(Transformer):
    """Filter-bank convolution with optional per-patch normalization and
    whitening fold-in (reference ``images/Convolver.scala:20-45``).

    ``filters`` is (num_filters, conv_size^2 * channels) in (dy, dx, c)
    feature order, pre-whitened by the caller exactly as in the reference
    (filters_normalized @ whitener.T); the whitener's means are subtracted
    from each normalized patch. Executes as pure XLA convolutions — see
    ``ops/image_ops.filter_bank_convolve``.
    """

    def __init__(
        self,
        filters: np.ndarray,
        img_height: int,
        img_width: int,
        img_channels: int,
        whitener: Optional["ZCAWhitener"] = None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
    ):
        self.filters = np.asarray(filters, dtype=np.float32)
        self.img_height = img_height
        self.img_width = img_width
        self.img_channels = img_channels
        self.whitener = whitener
        self.normalize_patches = normalize_patches
        self.var_constant = var_constant
        self.conv_size = int(
            round((self.filters.shape[1] / img_channels) ** 0.5)
        )

    def eq_key(self):
        return (
            Convolver,
            self.filters.tobytes(),
            self.img_height,
            self.img_width,
            self.img_channels,
            None if self.whitener is None else self.whitener.means.tobytes(),
            self.normalize_patches,
            self.var_constant,
        )

    def apply(self, img):
        means = None if self.whitener is None else jnp.asarray(self.whitener.means)
        return image_ops.filter_bank_convolve(
            img,
            jnp.asarray(self.filters),
            self.conv_size,
            self.img_channels,
            self.normalize_patches,
            means,
            self.var_constant,
        )

    # fitted-param protocol: the (whitened) filter bank is fitted per
    # run, so programs built over plain apply() bake it as constants and
    # recompile on every refit; threading it as arguments lets fused
    # featurizer chains share one compiled program across refits.
    def apply_params(self):
        params = self.__dict__.get("_jit_conv_params")
        if params is None:
            means = (None if self.whitener is None
                     else jnp.asarray(self.whitener.means))
            params = (jnp.asarray(self.filters), means)
            self.__dict__["_jit_conv_params"] = params  # _jit_*: unpickled
        return params

    def apply_with_params(self, params, img):
        filters, means = params
        return image_ops.filter_bank_convolve(
            img, filters, self.conv_size, self.img_channels,
            self.normalize_patches, means, self.var_constant,
        )

    def struct_key(self):
        return (Convolver, self.conv_size, self.img_channels,
                self.normalize_patches, self.var_constant,
                self.whitener is None)


class Windower(Transformer):
    """Dense sliding-window patch extraction (reference
    ``images/Windower.scala:14-55``). A 1->many node: each image yields
    all its windows, so the output dataset has n * num_windows items.
    Padding rows of the input batch map to trailing zero windows, so the
    true count stays exact."""

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def apply(self, img):
        w = image_ops.extract_windows(img, self.window_size, self.stride)
        nH, nW, S, _, C = w.shape
        return w.reshape(nH * nW, S, S, C)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        assert isinstance(ds, ArrayDataset)
        out = ds.map_batch(self._batched())
        data = out.data  # (P, num_windows, S, S, C)
        P, num_windows = data.shape[0], data.shape[1]
        flat = _flatten_leading(data)
        return ArrayDataset(
            flat, n=ds.n * num_windows, mesh=ds.mesh, _already_sharded=True
        )


class RandomPatcher(Transformer):
    """Uniformly random crops, ``num_patches`` per image (reference
    ``images/RandomPatcher.scala:17-46``). Deterministic per (seed, item
    index)."""

    def __init__(self, num_patches: int, patch_size_x: int, patch_size_y: int,
                 seed: int = 0):
        self.num_patches = num_patches
        self.patch_size_x = patch_size_x
        self.patch_size_y = patch_size_y
        self.seed = seed

    def _make_batch(self):
        px, py, npp = self.patch_size_x, self.patch_size_y, self.num_patches
        seed = self.seed

        def batch(imgs):
            P, H, W, C = imgs.shape
            keys = jax.vmap(jax.random.fold_in, (None, 0))(
                jax.random.PRNGKey(seed), jnp.arange(P)
            )

            def one(img, key):
                kx, ky = jax.random.split(key)
                xs = jax.random.randint(kx, (npp,), 0, H - px + 1)
                ys = jax.random.randint(ky, (npp,), 0, W - py + 1)

                def crop(x, y):
                    return jax.lax.dynamic_slice(img, (x, y, 0), (px, py, C))

                return jax.vmap(crop)(xs, ys)

            return jax.vmap(one)(imgs, keys)

        return batch

    def apply_dataset(self, ds: Dataset) -> Dataset:
        assert isinstance(ds, ArrayDataset)
        out = ds.map_batch(self._cached_jit("random_patch", self._make_batch))
        return ArrayDataset(
            _flatten_leading(out.data),
            n=ds.n * self.num_patches,
            mesh=ds.mesh,
            _already_sharded=True,
        )

    def abstract_eval(self, dep_specs):
        return _patcher_abstract_eval(
            self, dep_specs, self.patch_size_x, self.patch_size_y,
            self.num_patches)


class CenterCornerPatcher(Transformer):
    """Center + four corner crops, optionally with horizontal flips —
    test-time augmentation (reference ``images/CenterCornerPatcher.scala``).
    Yields 5 (or 10) patches per image."""

    def __init__(self, patch_size_x: int, patch_size_y: int, horizontal_flips: bool = False):
        self.patch_size_x = patch_size_x
        self.patch_size_y = patch_size_y
        self.horizontal_flips = horizontal_flips

    @property
    def patches_per_image(self) -> int:
        return 10 if self.horizontal_flips else 5

    def apply(self, img):
        H, W, C = img.shape
        px, py = self.patch_size_x, self.patch_size_y
        starts = [
            (0, 0),
            (0, W - py),
            (H - px, 0),
            (H - px, W - py),
            ((H - px) // 2, (W - py) // 2),
        ]
        crops = [img[x : x + px, y : y + py, :] for x, y in starts]
        if self.horizontal_flips:
            crops = crops + [c[:, ::-1, :] for c in crops]
        return jnp.stack(crops)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        assert isinstance(ds, ArrayDataset)
        out = ds.map_batch(self._batched())
        return ArrayDataset(
            _flatten_leading(out.data),
            n=ds.n * self.patches_per_image,
            mesh=ds.mesh,
            _already_sharded=True,
        )

    def abstract_eval(self, dep_specs):
        return _patcher_abstract_eval(
            self, dep_specs, self.patch_size_x, self.patch_size_y,
            self.patches_per_image)


def _patcher_abstract_eval(op, dep_specs, px, py, patches_per_image):
    """Shared static semantics of the cropping augmenters: each (H, W, C)
    image becomes ``patches_per_image`` items of (px, py, C), multiplying
    the dataset's item count."""
    from ...analysis.spec import DatasetSpec, Unknown

    (d,) = dep_specs
    if not isinstance(d, DatasetSpec):
        return Unknown(f"{type(op).__name__} is dataset-only")
    e = d.element
    if not (isinstance(e, jax.ShapeDtypeStruct) and len(e.shape) == 3):
        return Unknown("patcher input not an (H, W, C) image element")
    H, W, C = e.shape
    if H < px or W < py:
        raise ValueError(
            f"{type(op).__name__}: patch ({px}, {py}) larger than "
            f"input image ({H}, {W})")
    out = jax.ShapeDtypeStruct((px, py, C), e.dtype)
    n = None if d.n is None else d.n * patches_per_image
    return DatasetSpec(out, n=n, host=d.host, sparsity=1.0)


def _flip_h(img):
    return img[:, ::-1, :]


class RandomFlipper(Transformer):
    """Horizontal flip with probability p — the common specialization of
    RandomImageTransformer (reference
    ``images/RandomImageTransformer.scala:16-30`` used with
    ``ImageUtils.flipHorizontal``). Kept as its own class for a stable,
    picklable eq_key."""

    def __init__(self, prob: float = 0.5, seed: int = 0):
        self.prob = prob
        self.seed = seed

    def eq_key(self):
        return (RandomFlipper, self.prob, self.seed)

    def apply(self, img):
        return img

    def apply_dataset(self, ds: Dataset) -> Dataset:
        return RandomImageTransformer(
            self.prob, _flip_h, self.seed).apply_dataset(ds)


class LabelExtractor(Transformer):
    """(image, label) -> label (reference ``images/LabeledImageExtractors``)."""

    def apply(self, item):
        return item[1]


class ImageExtractor(Transformer):
    """(image, label) -> image."""

    def apply(self, item):
        return item[0]


def _flatten_leading(data):
    """(P, M, ...) -> (P*M, ...), preserving row sharding."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), data
    )


class RandomImageTransformer(Transformer):
    """Apply an image->image transform with probability p per item
    (reference ``images/RandomImageTransformer.scala:16-30``); the
    transform must be jax-traceable and shape-preserving. RandomFlipper
    is the common flip case."""

    def __init__(self, prob: float, transform, seed: int = 0):
        self.prob = prob
        self.transform = transform
        self.seed = seed

    def eq_key(self):
        # function objects are not picklable/stably-hashable; key on
        # identity (session-local reuse only, like untagged datasets)
        return (RandomImageTransformer, self.prob, self.seed,
                id(self.transform))

    def apply(self, img):
        return img

    def _make_batch(self):
        prob, seed, fn = self.prob, self.seed, self.transform

        def batch(imgs):
            P = imgs.shape[0]
            hit = jax.random.uniform(jax.random.PRNGKey(seed), (P,)) < prob
            changed = jax.vmap(fn)(imgs)
            return jnp.where(
                hit.reshape((-1,) + (1,) * (imgs.ndim - 1)), changed, imgs)

        return batch

    def apply_dataset(self, ds: Dataset) -> Dataset:
        assert isinstance(ds, ArrayDataset)
        return ds.map_batch(
            self._cached_jit("random_transform", self._make_batch))


class FusedConvRectifyPool(Transformer):
    """Fused Convolver >> SymmetricRectifier >> Pooler(sum) >> vectorize
    as one Pallas TPU kernel (``ops/pallas_kernels.fused_cifar_featurize``):
    the conv/rectifier intermediates never leave VMEM, which roughly
    doubles featurization throughput on the north-star CIFAR benchmark.
    Falls back to the composed XLA ops off-TPU. Same contract as
    Convolver: ``filters`` arrive pre-whitened by the caller
    (filters_normalized @ whitener.T); the whitener contributes only its
    means, subtracted post-normalization."""

    def __init__(self, filters, img_size: int, patch_size: int,
                 channels: int = 3, pool_stride: int = 13,
                 pool_size: int = 14, alpha: float = 0.25,
                 whitener=None, var_constant: float = 10.0):
        import numpy as _np

        self.filters = _np.asarray(filters, _np.float32)
        self.whitener_means = None
        if whitener is not None:
            self.whitener_means = _np.asarray(whitener.means, _np.float32)
        self.img_size = img_size
        self.patch_size = patch_size
        self.channels = channels
        self.pool_stride = pool_stride
        self.pool_size = pool_size
        self.alpha = alpha
        self.var_constant = var_constant

    def eq_key(self):
        return (FusedConvRectifyPool, self.filters.tobytes(),
                self.filters.shape, self.img_size, self.patch_size,
                self.channels, self.pool_stride, self.pool_size,
                self.alpha, self.var_constant,
                None if self.whitener_means is None
                else self.whitener_means.tobytes())

    def _fused_batch(self, imgs):
        from ...ops.pallas_kernels import fused_cifar_featurize

        means = None if self.whitener_means is None else jnp.asarray(
            self.whitener_means)
        return fused_cifar_featurize(
            imgs, jnp.asarray(self.filters), self.img_size,
            self.patch_size, self.channels, self.pool_stride,
            self.pool_size, self.var_constant, self.alpha,
            whitener_means=means)

    def apply(self, img):
        # single-item / off-TPU path: the composed ops
        from ...ops.image_ops import filter_bank_convolve, pool_image

        conv = filter_bank_convolve(
            img, jnp.asarray(self.filters), self.patch_size, self.channels,
            True,
            None if self.whitener_means is None
            else jnp.asarray(self.whitener_means),
            self.var_constant)
        pos = jnp.maximum(0.0, conv - self.alpha)
        neg = jnp.maximum(0.0, -conv - self.alpha)
        pooled = pool_image(
            jnp.concatenate([pos, neg], -1), self.pool_stride,
            self.pool_size, "identity", "sum")
        return pooled.reshape(-1)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from ...ops.pallas_kernels import use_pallas

        if isinstance(ds, ArrayDataset) and use_pallas():
            return ds.map_batch(self._fused_batch)
        return super().apply_dataset(ds)

    # fitted-param protocol (off-TPU composed path; the Pallas batch
    # path already takes filters as arguments): the fitted whitened
    # filter bank rides as a runtime argument so refits never recompile
    def apply_params(self):
        params = self.__dict__.get("_jit_conv_params")
        if params is None:
            means = (None if self.whitener_means is None
                     else jnp.asarray(self.whitener_means))
            params = (jnp.asarray(self.filters), means)
            self.__dict__["_jit_conv_params"] = params
        return params

    def apply_with_params(self, params, img):
        from ...ops.image_ops import filter_bank_convolve, pool_image

        filters, means = params
        conv = filter_bank_convolve(
            img, filters, self.patch_size, self.channels, True, means,
            self.var_constant)
        pos = jnp.maximum(0.0, conv - self.alpha)
        neg = jnp.maximum(0.0, -conv - self.alpha)
        pooled = pool_image(
            jnp.concatenate([pos, neg], -1), self.pool_stride,
            self.pool_size, "identity", "sum")
        return pooled.reshape(-1)

    def struct_key(self):
        return (FusedConvRectifyPool, self.filters.shape, self.img_size,
                self.patch_size, self.channels, self.pool_stride,
                self.pool_size, self.alpha, self.var_constant,
                self.whitener_means is None)
