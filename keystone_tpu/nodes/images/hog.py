"""Histogram of Oriented Gradients (reference
``nodes/images/HogExtractor.scala``, a port of Felzenszwalb/Girshick
voc-releaseX ``features.cc``).

Vectorized re-design: per-pixel channel selection, 18-way orientation
snapping, and the 4-cell bilinear histogram scatter are whole-image array
ops (one scatter-add instead of the reference's pixel loop), followed by
block normalization and the 32-dim feature assembly (18 contrast
sensitive + 9 insensitive + 4 texture + 1 truncation, reference
numFeatures = 27 + 4 + 1, HogExtractor.scala:203).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...workflow.transformer import Transformer

EPSILON = 1e-4
UU = np.array([1.0, 0.9397, 0.7660, 0.5, 0.1736,
               -0.1736, -0.5, -0.7660, -0.9397])
VV = np.array([0.0, 0.3420, 0.6428, 0.8660, 0.9848,
               0.9848, 0.8660, 0.6428, 0.3420])


@functools.partial(jax.jit, static_argnames=("bin_size", "nx", "ny"))
def _hog(img, bin_size, nx, ny):
    H, W, C = img.shape
    nvx, nvy = nx * bin_size, ny * bin_size

    # interior pixels 1..nv-2 (reference HogExtractor.scala:88-91)
    xs = np.arange(1, nvx - 1)
    ys = np.arange(1, nvy - 1)
    # gradients per channel at interior pixels (clamped reads)
    def px(x_idx, y_idx):
        return img[jnp.clip(x_idx, 0, H - 1)][:, jnp.clip(y_idx, 0, W - 1)]

    dx = px(xs + 1, ys) - px(xs - 1, ys)          # (nvx-2, nvy-2, C)
    dy = px(xs, ys + 1) - px(xs, ys - 1)

    mag2 = dx * dx + dy * dy
    # highest-magnitude channel wins; the reference scans channels 2..0
    # and keeps strictly-greater, so ties resolve to the LOWEST index
    best_c = jnp.argmax(mag2[..., ::-1], axis=-1)
    best_c = (C - 1) - best_c
    take = lambda a: jnp.take_along_axis(a, best_c[..., None], axis=-1)[..., 0]
    dx, dy = take(dx), take(dy)
    mag = jnp.sqrt(take(mag2))

    # orientation snap: interleave [d0, -d0, d1, -d1, ...] so argmax
    # reproduces the reference's first-strictly-greater scan order
    dots = dy[..., None] * UU[None, None, :] + dx[..., None] * VV[None, None, :]
    inter = jnp.stack([dots, -dots], axis=-1).reshape(dots.shape[:-1] + (18,))
    am = jnp.argmax(inter, axis=-1)
    orient = am // 2 + 9 * (am % 2)
    orient = jnp.where(jnp.max(inter, axis=-1) > 0.0, orient, 0)

    # bilinear scatter into (18, ny, nx) cell histograms
    xg, yg = np.meshgrid(xs, ys, indexing="ij")
    xp = (xg + 0.5) / bin_size - 0.5
    yp = (yg + 0.5) / bin_size - 0.5
    ixp = np.floor(xp).astype(np.int64)
    iyp = np.floor(yp).astype(np.int64)
    vx0 = jnp.asarray(xp - ixp)
    vy0 = jnp.asarray(yp - iyp)
    vx1, vy1 = 1.0 - vx0, 1.0 - vy0

    hist = jnp.zeros((18, ny, nx), jnp.float32)
    corners = [
        (ixp, iyp, vy1 * vx1),
        (ixp, iyp + 1, vy0 * vx1),
        (ixp + 1, iyp, vy1 * vx0),
        (ixp + 1, iyp + 1, vy0 * vx0),
    ]
    for cx, cy, w in corners:
        valid = (cx >= 0) & (cx < nx) & (cy >= 0) & (cy < ny)
        idx = (orient, jnp.asarray(np.clip(cy, 0, ny - 1)),
               jnp.asarray(np.clip(cx, 0, nx - 1)))
        hist = hist.at[idx].add(
            jnp.where(jnp.asarray(valid), w * mag, 0.0).astype(jnp.float32))

    # cell energies over combined opposite orientations
    comb = hist[:9] + hist[9:]
    norm = jnp.sum(comb * comb, axis=0)  # (ny, nx)

    nxf, nyf = max(nx - 2, 0), max(ny - 2, 0)
    # 2x2 block sums S[y, x] = norm[y:y+2, x:x+2].sum()
    S = norm[:-1, :-1] + norm[:-1, 1:] + norm[1:, :-1] + norm[1:, 1:]
    inv = lambda block: 1.0 / jnp.sqrt(block + EPSILON)
    n1 = inv(S[1:1 + nyf, 1:1 + nxf])
    n2 = inv(S[1:1 + nyf, 0:nxf])
    n3 = inv(S[0:nyf, 1:1 + nxf])
    n4 = inv(S[0:nyf, 0:nxf])

    ch = hist[:, 1:1 + nyf, 1:1 + nxf]  # center cell hists (18, nyf, nxf)
    h1 = jnp.minimum(ch * n1, 0.2)
    h2 = jnp.minimum(ch * n2, 0.2)
    h3 = jnp.minimum(ch * n3, 0.2)
    h4 = jnp.minimum(ch * n4, 0.2)
    sensitive = 0.5 * (h1 + h2 + h3 + h4)          # (18, nyf, nxf)
    t1, t2, t3, t4 = (h.sum(axis=0) for h in (h1, h2, h3, h4))

    cs = ch[:9] + ch[9:]
    insensitive = 0.5 * (
        jnp.minimum(cs * n1, 0.2) + jnp.minimum(cs * n2, 0.2)
        + jnp.minimum(cs * n3, 0.2) + jnp.minimum(cs * n4, 0.2))

    texture = 0.2357 * jnp.stack([t1, t2, t3, t4])  # (4, nyf, nxf)
    trunc = jnp.zeros((1, nyf, nxf), jnp.float32)

    feats = jnp.concatenate([sensitive, insensitive, texture, trunc], axis=0)
    # rows ordered y + x*nyf (reference computeFeaturesFromHist)
    return feats.transpose(2, 1, 0).reshape(nxf * nyf, 32)


class HogExtractor(Transformer):
    """32-dim HOG cell features; output (numCells, 32) float
    (reference ``HogExtractor.scala:33-70``)."""

    def __init__(self, bin_size: int = 8):
        self.bin_size = bin_size

    def apply(self, img):
        H, W = int(img.shape[0]), int(img.shape[1])
        nx = int(round(H / self.bin_size))
        ny = int(round(W / self.bin_size))
        return _hog(img.astype(jnp.float32), self.bin_size, nx, ny)
