
from .core import (  # noqa: E402
    CenterCornerPatcher,
    Convolver,
    Cropper,
    FusedConvRectifyPool,
    GrayScaler,
    ImageExtractor,
    ImageVectorizer,
    LabelExtractor,
    PixelScaler,
    Pooler,
    RandomFlipper,
    RandomImageTransformer,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
)
from .multilabel import (  # noqa: E402
    MultiLabelExtractor,
    MultiLabeledImageExtractor,
)
from .extractors import (  # noqa: E402
    BatchSIFTExtractor,
    LCSExtractor,
    SIFTExtractor,
)
from .fisher_vector import (  # noqa: E402
    EncEvalGMMFisherVectorEstimator,
    FisherVector,
    GMMFisherVectorEstimator,
    ScalaGMMFisherVectorEstimator,
)
from .daisy import DaisyExtractor  # noqa: E402
from .hog import HogExtractor  # noqa: E402
