
from .extractors import (  # noqa: E402
    BatchSIFTExtractor,
    LCSExtractor,
    SIFTExtractor,
)
from .fisher_vector import (  # noqa: E402
    EncEvalGMMFisherVectorEstimator,
    FisherVector,
    GMMFisherVectorEstimator,
    ScalaGMMFisherVectorEstimator,
)
from .daisy import DaisyExtractor  # noqa: E402
from .hog import HogExtractor  # noqa: E402
