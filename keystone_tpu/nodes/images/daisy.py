"""DAISY dense descriptors (reference
``nodes/images/DaisyExtractor.scala``; Tola, Lepetit, Fua, PAMI 2010).

Pipeline: oriented gradient maps (H rectified directional derivatives),
stacked Gaussian blur layers (each level blurs the previous, so level l
carries cumulative sigma), then per-keypoint histograms sampled at the
center plus T ring points per level, each L2-normalized. All convolution
work is separable 'same' convs (one jitted program); histogram sampling
is a static gather at precomputed integer offsets.
"""
from __future__ import annotations

import functools
import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...workflow.transformer import Transformer

FEATURE_THRESHOLD = 1e-8
CONV_THRESHOLD = 1e-6


def conv2d_same(img: jax.Array, fx: np.ndarray, fy: np.ndarray) -> jax.Array:
    """Zero-padded separable 'same' true convolution of (H, W), matching
    ``ImageUtils.conv2D`` (reference ImageUtils.scala:226-344): pad low =
    floor((L-1)/2), kernels flipped."""
    kx = jnp.asarray(np.asarray(fx, np.float32)[::-1].copy())
    ky = jnp.asarray(np.asarray(fy, np.float32)[::-1].copy())
    lx, ly = len(fx), len(fy)
    plx, phx = (lx - 1) // 2, lx - 1 - (lx - 1) // 2
    ply, phy = (ly - 1) // 2, ly - 1 - (ly - 1) // 2
    x = jnp.pad(img, ((plx, phx), (ply, phy)))[None, None]
    x = jax.lax.conv_general_dilated(x, kx.reshape(1, 1, -1, 1), (1, 1), "VALID")
    x = jax.lax.conv_general_dilated(x, ky.reshape(1, 1, 1, -1), (1, 1), "VALID")
    return x[0, 0]


def _daisy_kernels(daisy_q: int, daisy_r: int) -> List[np.ndarray]:
    """Incremental Gaussian kernels (reference DaisyExtractor.scala:50-64):
    sigma^2 ladder (R*n / 2Q)^2, each kernel covering the difference."""
    sigma_sq = [(daisy_r * n / (2.0 * daisy_q)) ** 2
                for n in range(daisy_q + 1)]
    diffs = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
    kernels = []
    for t in diffs:
        radius = int(math.ceil(math.sqrt(
            -2 * t * math.log(CONV_THRESHOLD) - t * math.log(2 * math.pi * t))))
        n = np.arange(-radius, radius + 1, dtype=np.float64)
        k = np.exp(-(n ** 2) / (2 * t)) / math.sqrt(2 * math.pi * t)
        kernels.append(k)
    return kernels


class DaisyExtractor(Transformer):
    """DAISY on a regular grid; output (H*(T*Q+1), numKeypoints) float
    (reference ``DaisyExtractor.scala:28-201``)."""

    def __init__(self, daisy_t: int = 8, daisy_q: int = 3, daisy_r: int = 7,
                 daisy_h: int = 8, pixel_border: int = 16, stride: int = 4):
        self.daisy_t = daisy_t
        self.daisy_q = daisy_q
        self.daisy_r = daisy_r
        self.daisy_h = daisy_h
        self.pixel_border = pixel_border
        self.stride = stride

    @property
    def feature_size(self) -> int:
        return self.daisy_h * (self.daisy_t * self.daisy_q + 1)

    def apply(self, img):
        if img.ndim == 3:
            img = img[..., 0]
        return _daisy(
            img.astype(jnp.float32), int(img.shape[0]), int(img.shape[1]),
            self.daisy_t, self.daisy_q, self.daisy_r, self.daisy_h,
            self.pixel_border, self.stride)


@functools.partial(jax.jit, static_argnames=(
    "height", "width", "T", "Q", "R", "H", "border", "stride"))
def _daisy(img, height, width, T, Q, R, H, border, stride):
    # oriented gradient maps (reference :108-136)
    f1 = np.array([1.0, 0.0, -1.0])
    f2 = np.array([1.0, 2.0, 1.0])
    ix = conv2d_same(img, f1, f2)
    iy = conv2d_same(img, f2, f1)
    kernels = _daisy_kernels(Q, R)

    layers = []  # (Q, H) images
    for h in range(H):
        angle = 2.0 * np.pi * h / H
        g0 = jnp.maximum(np.cos(angle) * ix + np.sin(angle) * iy, 0.0)
        level = conv2d_same(g0, kernels[0], kernels[0])
        per_level = [level]
        for l in range(1, Q):
            level = conv2d_same(level, kernels[l], kernels[l])
            per_level.append(level)
        layers.append(per_level)
    # stack to (Q, H, height, width)
    stack = jnp.stack(
        [jnp.stack([layers[h][l] for h in range(H)]) for l in range(Q)])

    xs = np.arange(border, height - border, stride)
    ys = np.arange(border, width - border, stride)
    xx, yy = np.meshgrid(xs, ys, indexing="ij")
    xx, yy = xx.ravel(), yy.ravel()  # keypoints, x-major like the reference

    def norm_hist(h):  # (N, H) -> L2 normalized, zeroed when tiny
        n = jnp.linalg.norm(h, axis=1, keepdims=True)
        return jnp.where(n > FEATURE_THRESHOLD, h / jnp.maximum(n, 1e-30), 0.0)

    feats = []
    # center histogram: layer 0 at the keypoint (reference getCenterHist)
    center = stack[0][:, xx, yy].T  # (N, H)
    feats.append(norm_hist(center))

    ring = np.zeros((Q, T, 2), np.int64)
    for l in range(Q):
        rad = R * (1.0 + l) / Q
        for t in range(T):
            theta = 2.0 * np.pi * (t - 1) / T
            ring[l, t, 0] = int(round(rad * math.sin(theta)))
            ring[l, t, 1] = int(round(rad * math.cos(theta)))

    # feature layout (reference :160-186): center at [0:H], then ring
    # histogram for angle t, level l at H + t*Q*H + l*H
    ring_feats = {}
    for l in range(Q):
        for t in range(T):
            px = np.clip(xx + ring[l, t, 0], 0, height - 1)
            py = np.clip(yy + ring[l, t, 1], 0, width - 1)
            ring_feats[(t, l)] = norm_hist(stack[l][:, px, py].T)
    for t in range(T):
        for l in range(Q):
            feats.append(ring_feats[(t, l)])

    out = jnp.concatenate(feats, axis=1)  # (N, H*(T*Q+1))
    return out.T.astype(jnp.float32)
