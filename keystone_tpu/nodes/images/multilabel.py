"""Multi-label image extractors (reference
``nodes/images/LabeledImageExtractors.scala``).

Items are :class:`~keystone_tpu.loaders.image_loader_utils.MultiLabeledImage`
host objects; label sets are ragged, so ``MultiLabelExtractor`` pads them
to a fixed width with -1 (the TPU layout consumed by
``ClassLabelIndicatorsFromIntArrayLabels``).
"""
from __future__ import annotations

import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset, HostDataset
from ...workflow.transformer import Transformer


class MultiLabelExtractor(Transformer):
    """MultiLabeledImage -> padded int label array
    (reference ``LabeledImageExtractors.scala``)."""

    def apply(self, item):
        return np.asarray(item.labels, dtype=np.int32)

    def apply_dataset(self, ds: Dataset) -> Dataset:
        items = ds.collect()
        width = max((len(it.labels) for it in items), default=1) or 1
        padded = np.full((len(items), width), -1, dtype=np.int32)
        for i, it in enumerate(items):
            padded[i, : len(it.labels)] = np.asarray(it.labels, np.int32)
        return ArrayDataset.from_numpy(padded)


class MultiLabeledImageExtractor(Transformer):
    """MultiLabeledImage -> image array (host dataset: images are ragged)."""

    def apply(self, item):
        return item.image

    def apply_dataset(self, ds: Dataset) -> Dataset:
        return HostDataset([it.image for it in ds.collect()])
