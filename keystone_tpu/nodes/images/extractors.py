"""Dense feature extractors: SIFT and LCS (reference
``nodes/images/external/SIFTExtractor.scala``,
``nodes/images/LCSExtractor.scala``).

Both return a per-image (D, numDesc) float matrix — the reference's
column-per-descriptor layout — computed as jitted conv + gather programs
instead of JNI calls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.sift import dense_sift, sift_descriptor_count
from ...workflow.transformer import Transformer


class SIFTExtractor(Transformer):
    """Multi-scale dense SIFT (reference
    ``SIFTExtractor.scala:27-34`` / ``VLFeat.cxx``): input is a grayscale
    (H, W) or (H, W, 1) image scaled to [0, 1]; output (128, numDesc)."""

    def __init__(self, step: int = 4, bin_size: int = 6,
                 num_scales: int = 5, scale_step: int = 0):
        self.step = step
        self.bin_size = bin_size
        self.num_scales = num_scales
        self.scale_step = scale_step

    def apply(self, img):
        if img.ndim == 3:
            img = img[..., 0]
        return dense_sift(
            img, self.step, self.bin_size, self.num_scales, self.scale_step)

    def descriptor_count(self, height: int, width: int) -> int:
        return sift_descriptor_count(
            height, width, self.step, self.bin_size,
            self.num_scales, self.scale_step)

    # -- static HBM planning (analysis.resources) --------------------------
    def resource_effect(self, dep_specs, out_spec, data_shards=1):
        """SIFT nodes charge their per-config band-operator constants
        (smoothing + sampling matrices, resident whether they feed the
        einsum or the Pallas banded kernel) as a one-off transient —
        the lru caches keep the arrays alive across every image of a
        config."""
        import dataclasses

        from ...analysis.resources import (
            sift_band_operator_nbytes,
            spec_effect,
        )

        element = (getattr(dep_specs[0], "element", None)
                   if dep_specs else None)
        if not (isinstance(element, jax.ShapeDtypeStruct)
                and len(element.shape) >= 2):
            return None
        base = spec_effect(out_spec, data_shards)
        extra = sift_band_operator_nbytes(
            int(element.shape[0]), int(element.shape[1]), self.step,
            self.bin_size, self.num_scales, self.scale_step)
        return dataclasses.replace(
            base, transient_nbytes=base.transient_nbytes + extra,
            note=(base.note + "; " if base.note else "")
            + "SIFT band-operator constants")


class BatchSIFTExtractor(SIFTExtractor):
    """SIFT over per-item image batches via vmap (fixed image size)."""

    def apply_dataset(self, ds):
        return ds.map(self.apply)


@functools.partial(
    jax.jit, static_argnames=("stride", "stride_start", "sub_patch_size"))
def _lcs(img, stride, stride_start, sub_patch_size):
    """Local color statistics (reference ``LCSExtractor.scala:50-130``):
    per-channel box-filter means and stddevs, sampled on a keypoint grid
    at a 4x4 neighborhood of sub-patch offsets -> (96, numKeypoints)."""
    H, W, C = img.shape
    k = jnp.full((sub_patch_size,), 1.0 / sub_patch_size)

    def box2d(ch):
        # 'same' separable box filter, zero padding like ImageUtils.conv2D
        r0 = (sub_patch_size - 1) // 2
        r1 = sub_patch_size - 1 - r0
        x = jnp.pad(ch, ((r0, r1), (r0, r1)))[None, None]
        kr = k.reshape(1, 1, -1, 1)
        kc = k.reshape(1, 1, 1, -1)
        x = jax.lax.conv_general_dilated(x, kr, (1, 1), "VALID")
        x = jax.lax.conv_general_dilated(x, kc, (1, 1), "VALID")
        return x[0, 0]

    chans = [img[:, :, c] for c in range(C)]
    means = [box2d(ch) for ch in chans]
    stds = [
        jnp.sqrt(jnp.maximum(box2d(ch * ch) - m * m, 0.0))
        for ch, m in zip(chans, means)
    ]

    xs = np.arange(stride_start, H - stride_start, stride)
    ys = np.arange(stride_start, W - stride_start, stride)
    # sub-patch offsets: start = -2s + s//2 - 1, end = s + s//2 - 1, step s
    start = -2 * sub_patch_size + sub_patch_size // 2 - 1
    end = sub_patch_size + sub_patch_size // 2 - 1
    offs = np.arange(start, end + 1, sub_patch_size)

    xx, yy = np.meshgrid(xs, ys, indexing="ij")  # keypoints (x-major)
    xx, yy = xx.ravel(), yy.ravel()

    rows = []
    for c in range(C):
        for nx in offs:
            for ny in offs:
                px = np.clip(xx + nx, 0, H - 1)
                py = np.clip(yy + ny, 0, W - 1)
                rows.append(means[c][px, py])
                rows.append(stds[c][px, py])
    return jnp.stack(rows).astype(jnp.float32)  # (C*16*2, numKeypoints)


class LCSExtractor(Transformer):
    """Local Color Statistics on a regular grid (reference
    ``LCSExtractor.scala:26-130``; Clinchant et al. 2007): 4x4 sub-region
    means + stddevs of each channel -> 96-dim descriptors (for 3
    channels). Input (H, W, C) image; output (96, numKeypoints)."""

    def __init__(self, stride: int = 4, stride_start: int = 16,
                 sub_patch_size: int = 6):
        self.stride = stride
        self.stride_start = stride_start
        self.sub_patch_size = sub_patch_size

    def apply(self, img):
        return _lcs(img, self.stride, self.stride_start, self.sub_patch_size)
