"""Fisher vector encoding (reference ``nodes/images/FisherVector.scala``
and the enceval JNI variant ``nodes/images/external/FisherVector.scala`` /
``cpp/EncEval.cxx``).

The FV of a descriptor matrix under a diagonal GMM, in the s0/s1/s2
moment form of the Sanchez et al. survey (``FisherVector.scala:33-52``):

    q  = GMM posteriors               (nDesc, K)
    s0 = mean(q)                      (K,)
    s1 = X q / nDesc                  (D, K)
    s2 = (X*X) q / nDesc              (D, K)
    fv1 = (s1 - means s0) / (sqrt(vars) sqrt(w))
    fv2 = (s2 - 2 means s1 + (means^2 - vars) s0) / (vars sqrt(2 w))

One jitted program: the q/s1/s2 GEMMs are the hot path and map straight
onto the MXU — this *is* the TPU-native "native" implementation, so the
reference's scala-vs-enceval split becomes jit-per-item vs batched-vmap.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import Dataset
from ...workflow.estimator import Estimator
from ...workflow.optimizable import NodeChoice, OptimizableEstimator
from ...workflow.transformer import Transformer
from ..learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    _posteriors,
)


@functools.partial(jax.jit, static_argnames=())
def _fisher_vector(X, means, variances, weights, weight_threshold):
    """X is (D, nDesc); means/variances (D, K); weights (K,)."""
    n_desc = X.shape[1]
    q = _posteriors(
        X.T, means.T, variances.T, weights, weight_threshold
    )  # (nDesc, K)
    s0 = jnp.mean(q, axis=0)                      # (K,)
    s1 = (X @ q) / n_desc                         # (D, K)
    s2 = ((X * X) @ q) / n_desc                   # (D, K)
    sqrt_w = jnp.sqrt(weights)
    fv1 = (s1 - means * s0[None, :]) / (jnp.sqrt(variances) * sqrt_w[None, :])
    fv2 = (s2 - 2.0 * means * s1 + (means * means - variances) * s0[None, :]) \
        / (variances * jnp.sqrt(2.0 * weights)[None, :])
    return jnp.concatenate([fv1, fv2], axis=1)    # (D, 2K)


class FisherVector(Transformer):
    """FV transformer: (D, nDesc) descriptor matrix -> (D, 2K) matrix
    (reference ``FisherVector.scala:22-54``)."""

    def __init__(self, gmm: GaussianMixtureModel):
        self.gmm = gmm
        # plain config copy: struct-keyed programs capture an array-free
        # shim (config_shim drops the nested gmm node), and
        # apply_with_params may only read config attributes
        self.weight_threshold = gmm.weight_threshold

    def eq_key(self):
        return (FisherVector, id(self.gmm))

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)

    # fitted-param protocol (PERFORMANCE.md rule 6): a refitted GMM
    # codebook never recompiles the FV encoder
    def apply_params(self):
        params = self.__dict__.get("_jit_fv_params")
        if params is None:
            params = (jnp.asarray(self.gmm.means),
                      jnp.asarray(self.gmm.variances),
                      jnp.asarray(self.gmm.weights))
            self.__dict__["_jit_fv_params"] = params
        return params

    def apply_with_params(self, params, x):
        means, variances, weights = params
        return _fisher_vector(
            x.astype(jnp.float32), means, variances, weights,
            self.weight_threshold,
        )

    def struct_key(self):
        return (FisherVector, self.weight_threshold)


def _gmm_from_columns(ds: Dataset, k: int,
                      seed: Optional[int] = None) -> GaussianMixtureModel:
    """Fit the GMM treating every column of every item as a sample
    (reference ``ScalaGMMFisherVectorEstimator``,
    ``FisherVector.scala:67-73``)."""
    from ...parallel.dataset import ArrayDataset

    items = ds.collect()
    cols = np.concatenate(
        [np.asarray(m, np.float32).T for m in items], axis=0)
    est = GaussianMixtureModelEstimator(k, seed=seed or 0)
    return est.fit(ArrayDataset.from_numpy(cols))


def _fisher_abstract_fit(k: int):
    """Fitted FV encoder spec: (D, nDesc) descriptor matrix -> (D, 2K)."""
    import jax

    from ...analysis.spec import Unknown

    def apply_element(element):
        if isinstance(element, jax.ShapeDtypeStruct) and len(
                element.shape) == 2:
            return jax.ShapeDtypeStruct(
                (int(element.shape[0]), 2 * k), np.float32)
        return Unknown("fisher-vector input not a (D, nDesc) matrix")

    return apply_element


def _fisher_fitted_nbytes(k: int, dep_specs):
    """Fitted GMM: means + covariances (D, K) f32 each + weights (K,),
    D from the input element's descriptor axis."""
    import jax

    element = getattr(dep_specs[0], "element", None) if dep_specs else None
    if not (isinstance(element, jax.ShapeDtypeStruct)
            and len(element.shape) == 2):
        return None
    d = float(element.shape[0])
    return 4.0 * (2.0 * d * k + k)


class ScalaGMMFisherVectorEstimator(Estimator):
    """Per-item-jit FV estimator (reference ``FisherVector.scala:67-73``;
    the name mirrors the reference's scala implementation)."""

    def __init__(self, k: int):
        self.k = k

    def abstract_fit(self, dep_specs):
        return _fisher_abstract_fit(self.k)

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        return _fisher_fitted_nbytes(self.k, dep_specs)

    def _fit(self, ds: Dataset) -> FisherVector:
        return FisherVector(_gmm_from_columns(ds, self.k))


class EncEvalGMMFisherVectorEstimator(ScalaGMMFisherVectorEstimator):
    """Counterpart of the reference's native enceval estimator
    (``external/FisherVector.scala:17-55``): same GMM fit, same FV math —
    on TPU the jitted GEMM formulation IS the fast native path, so this
    is the scala variant under the reference's native name."""


class GMMFisherVectorEstimator(OptimizableEstimator):
    """Auto-choosing FV estimator (reference ``FisherVector.scala:85-94``:
    picks the native implementation when k >= 32)."""

    def __init__(self, k: int):
        self.k = k

    def abstract_fit(self, dep_specs):
        return _fisher_abstract_fit(self.k)

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        return _fisher_fitted_nbytes(self.k, dep_specs)

    @property
    def default(self) -> Estimator:
        return ScalaGMMFisherVectorEstimator(self.k)

    def optimize(self, sample: Dataset, n: int, num_machines: int) -> NodeChoice:
        if self.k >= 32:
            return NodeChoice(EncEvalGMMFisherVectorEstimator(self.k))
        return NodeChoice(ScalaGMMFisherVectorEstimator(self.k))

    def optimize_static(self, spec, n: int, num_machines: int):
        # the choice depends only on k: always statically resolvable
        return self.optimize(None, n, num_machines)
