"""Fisher vector encoding (reference ``nodes/images/FisherVector.scala``
and the enceval JNI variant ``nodes/images/external/FisherVector.scala`` /
``cpp/EncEval.cxx``).

The FV of a descriptor matrix under a diagonal GMM, in the s0/s1/s2
moment form of the Sanchez et al. survey (``FisherVector.scala:33-52``):

    q  = GMM posteriors               (nDesc, K)
    s0 = mean(q)                      (K,)
    s1 = X q / nDesc                  (D, K)
    s2 = (X*X) q / nDesc              (D, K)
    fv1 = (s1 - means s0) / (sqrt(vars) sqrt(w))
    fv2 = (s2 - 2 means s1 + (means^2 - vars) s0) / (vars sqrt(2 w))

One jitted program: the q/s1/s2 GEMMs are the hot path and map straight
onto the MXU — this *is* the TPU-native "native" implementation, so the
reference's scala-vs-enceval split becomes jit-per-item vs batched-vmap.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import Dataset
from ...workflow.estimator import Estimator
from ...workflow.optimizable import NodeChoice, OptimizableEstimator
from ...workflow.transformer import Transformer
from ..learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    _posteriors,
)


def _fv_moment_sums(X, means, variances, weights, weight_threshold,
                    kernel_mode=None):
    """Raw posterior moment sums ``(sum q, X q, (X*X) q)`` of a
    (D, nDesc) descriptor matrix — the FV encoder's hot path.

    Dispatch (``kernel_mode=None`` = auto): the fused Pallas kernel on
    TPU when its accumulators fit VMEM
    (``ops.pallas_kernels.fv_moments_pallas`` — posteriors computed
    tile-by-tile in VMEM, the (nDesc, K) posterior matrix never written
    to HBM), else the split einsum fallback (the pre-kernel
    implementation, bit-identical: one posterior program + three moment
    GEMMs through HBM). ``"pallas_interpret"`` runs the kernel body on
    the CPU interpreter (tier-1/parity-gate path); ``"einsum"`` forces
    the fallback."""
    from ...ops.pallas_kernels import (
        fv_fits_vmem,
        fv_moments_pallas,
        use_pallas,
    )

    d, k = means.shape
    mode = kernel_mode
    if mode is None:
        mode = ("pallas" if use_pallas() and fv_fits_vmem(d, k)
                else "einsum")
    if mode in ("pallas", "pallas_interpret"):
        return fv_moments_pallas(
            X, means, variances, weights, threshold=weight_threshold,
            interpret=(mode == "pallas_interpret"))
    q = _posteriors(
        X.T, means.T, variances.T, weights, weight_threshold
    )  # (nDesc, K)
    return jnp.sum(q, axis=0), X @ q, (X * X) @ q


@functools.partial(
    jax.jit, static_argnames=("weight_threshold", "kernel_mode"))
def _fisher_vector(X, means, variances, weights, weight_threshold,
                   kernel_mode=None):
    """X is (D, nDesc); means/variances (D, K); weights (K,)."""
    n_desc = X.shape[1]
    q_sum, s1_sum, s2_sum = _fv_moment_sums(
        X, means, variances, weights, weight_threshold, kernel_mode)
    s0 = q_sum / n_desc                           # (K,)
    s1 = s1_sum / n_desc                          # (D, K)
    s2 = s2_sum / n_desc                          # (D, K)
    sqrt_w = jnp.sqrt(weights)
    fv1 = (s1 - means * s0[None, :]) / (jnp.sqrt(variances) * sqrt_w[None, :])
    fv2 = (s2 - 2.0 * means * s1 + (means * means - variances) * s0[None, :]) \
        / (variances * jnp.sqrt(2.0 * weights)[None, :])
    return jnp.concatenate([fv1, fv2], axis=1)    # (D, 2K)


class FisherVector(Transformer):
    """FV transformer: (D, nDesc) descriptor matrix -> (D, 2K) matrix
    (reference ``FisherVector.scala:22-54``)."""

    def __init__(self, gmm: GaussianMixtureModel):
        self.gmm = gmm
        # plain config copy: struct-keyed programs capture an array-free
        # shim (config_shim drops the nested gmm node), and
        # apply_with_params may only read config attributes
        self.weight_threshold = gmm.weight_threshold

    def eq_key(self):
        return (FisherVector, id(self.gmm))

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)

    # fitted-param protocol (PERFORMANCE.md rule 6): a refitted GMM
    # codebook never recompiles the FV encoder
    def apply_params(self):
        params = self.__dict__.get("_jit_fv_params")
        if params is None:
            params = (jnp.asarray(self.gmm.means),
                      jnp.asarray(self.gmm.variances),
                      jnp.asarray(self.gmm.weights))
            self.__dict__["_jit_fv_params"] = params
        return params

    def apply_with_params(self, params, x):
        means, variances, weights = params
        return _fisher_vector(
            x.astype(jnp.float32), means, variances, weights,
            self.weight_threshold,
        )

    def struct_key(self):
        return (FisherVector, self.weight_threshold)

    # -- static HBM planning (analysis.resources) --------------------------
    def resource_effect(self, dep_specs, out_spec, data_shards=1):
        """A pre-fitted FV node charges the same apply workspace the
        estimator's Delegate node would (fused-kernel accumulators or
        the fallback's posterior matrix)."""
        from ...analysis.resources import transform_workspace_effect

        return transform_workspace_effect(
            _fisher_apply_transient(self.gmm.k), dep_specs, out_spec,
            data_shards)


def _gmm_from_columns(ds: Dataset, k: int,
                      seed: Optional[int] = None) -> GaussianMixtureModel:
    """Fit the GMM treating every column of every item as a sample
    (reference ``ScalaGMMFisherVectorEstimator``,
    ``FisherVector.scala:67-73``)."""
    from ...parallel.dataset import ArrayDataset

    items = ds.collect()
    cols = np.concatenate(
        [np.asarray(m, np.float32).T for m in items], axis=0)
    est = GaussianMixtureModelEstimator(k, seed=seed or 0)
    return est.fit(ArrayDataset.from_numpy(cols))


def _fisher_abstract_fit(k: int):
    """Fitted FV encoder spec: (D, nDesc) descriptor matrix -> (D, 2K)."""
    import jax

    from ...analysis.spec import Unknown

    def apply_element(element):
        if isinstance(element, jax.ShapeDtypeStruct) and len(
                element.shape) == 2:
            return jax.ShapeDtypeStruct(
                (int(element.shape[0]), 2 * k), np.float32)
        return Unknown("fisher-vector input not a (D, nDesc) matrix")

    return apply_element


def _fisher_fitted_nbytes(k: int, dep_specs):
    """Fitted GMM: means + covariances (D, K) f32 each + weights (K,),
    D from the input element's descriptor axis."""
    import jax

    element = getattr(dep_specs[0], "element", None) if dep_specs else None
    if not (isinstance(element, jax.ShapeDtypeStruct)
            and len(element.shape) == 2):
        return None
    d = float(element.shape[0])
    return 4.0 * (2.0 * d * k + k)


def _fisher_apply_transient(k: int):
    """Per-item apply workspace for the HBM planner: the fused-kernel
    moment accumulators when the Pallas dispatch will take them, else
    the (nDesc, K) posterior matrix the split fallback materializes
    (``analysis.resources.fv_apply_transient_nbytes`` mirrors the
    runtime dispatch)."""
    import jax

    from ...analysis.resources import fv_apply_transient_nbytes

    def workspace(element):
        if not (isinstance(element, jax.ShapeDtypeStruct)
                and len(element.shape) == 2):
            return None
        return fv_apply_transient_nbytes(
            int(element.shape[0]), k, int(element.shape[1]))

    return workspace


class ScalaGMMFisherVectorEstimator(Estimator):
    """Per-item-jit FV estimator (reference ``FisherVector.scala:67-73``;
    the name mirrors the reference's scala implementation)."""

    def __init__(self, k: int):
        self.k = k

    def abstract_fit(self, dep_specs):
        return _fisher_abstract_fit(self.k)

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        return _fisher_fitted_nbytes(self.k, dep_specs)

    def abstract_apply_transient(self, dep_specs):
        return _fisher_apply_transient(self.k)

    def _fit(self, ds: Dataset) -> FisherVector:
        return FisherVector(_gmm_from_columns(ds, self.k))


class EncEvalGMMFisherVectorEstimator(ScalaGMMFisherVectorEstimator):
    """Counterpart of the reference's native enceval estimator
    (``external/FisherVector.scala:17-55``): same GMM fit, same FV math —
    on TPU the jitted GEMM formulation IS the fast native path, so this
    is the scala variant under the reference's native name."""


class GMMFisherVectorEstimator(OptimizableEstimator):
    """Auto-choosing FV estimator (reference ``FisherVector.scala:85-94``:
    picks the native implementation when k >= 32)."""

    def __init__(self, k: int):
        self.k = k

    def abstract_fit(self, dep_specs):
        return _fisher_abstract_fit(self.k)

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        return _fisher_fitted_nbytes(self.k, dep_specs)

    def abstract_apply_transient(self, dep_specs):
        return _fisher_apply_transient(self.k)

    @property
    def default(self) -> Estimator:
        return ScalaGMMFisherVectorEstimator(self.k)

    def optimize(self, sample: Dataset, n: int, num_machines: int) -> NodeChoice:
        if self.k >= 32:
            return NodeChoice(EncEvalGMMFisherVectorEstimator(self.k))
        return NodeChoice(ScalaGMMFisherVectorEstimator(self.k))

    def optimize_static(self, spec, n: int, num_machines: int):
        # the choice depends only on k: always statically resolvable
        return self.optimize(None, n, num_machines)
