"""Linear models and least-squares estimators.

TPU-native re-designs of reference ``nodes/learning/LinearMapper.scala``
and ``nodes/learning/BlockLinearMapper.scala`` (SURVEY.md section 2.3):
the Spark Gram-accumulate + driver-Cholesky becomes a sharded GEMM +
all-reduce + replicated Cholesky, and block coordinate descent runs as one
jitted program with per-block Gram psums.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import linalg
from ...parallel.dataset import ensure_array, ArrayDataset, Dataset
from ...workflow.label_estimator import LabelEstimator
from ...workflow.transformer import Transformer
from ..stats import StandardScalerModel


class LinearMapper(Transformer):
    """out = x_model^T in (+ b), with optional feature scaler
    (reference ``LinearMapper.scala:18-62``)."""

    def __init__(
        self,
        weights: np.ndarray,
        intercept: Optional[np.ndarray] = None,
        feature_scaler: Optional[StandardScalerModel] = None,
    ):
        self.weights = np.asarray(weights)
        self.intercept = None if intercept is None else np.asarray(intercept)
        self.feature_scaler = feature_scaler

    def apply(self, x):
        if self.feature_scaler is not None:
            x = self.feature_scaler.apply(x)
        out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out


class LinearMapEstimator(LabelEstimator):
    """OLS/ridge via distributed normal equations on mean-centered features
    and labels; intercept = label mean (reference
    ``LinearMapper.scala:71-98``)."""

    def __init__(self, lam: Optional[float] = None):
        self.lam = lam

    def _fit(self, ds: Dataset, labels: Dataset) -> LinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        n = ds.n
        X, Y = ds.data, labels.data
        x_mean = np.asarray(linalg.distributed_mean(X, n))
        y_mean = np.asarray(linalg.distributed_mean(Y, n))
        W = np.asarray(
            _centered_normal_equations(
                X, Y, jnp.asarray(x_mean), jnp.asarray(y_mean),
                ds.mask, float(self.lam or 0.0),
            )
        )
        return LinearMapper(
            W,
            intercept=y_mean,
            feature_scaler=StandardScalerModel(x_mean),
        )

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        """Reference cost model (LinearMapper.scala:100-115)."""
        flops = n * d * (d + k) / num_machines
        bytes_scanned = n * d / num_machines + d * d
        network = d * (d + k)
        return max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network


@jax.jit
def _centered_normal_equations(X, Y, x_mean, y_mean, mask, lam):
    m = mask[:, None].astype(X.dtype)
    Xc = (X - x_mean) * m
    Yc = (Y - y_mean) * m
    return linalg.ridge_cho_solve(linalg.gram(Xc), linalg.cross(Xc, Yc), lam)


class BlockLinearMapper(Transformer):
    """Block-partitioned linear model (reference
    ``BlockLinearMapper.scala:22-73``).

    The reference stores ``Seq[DenseMatrix]`` blocks and applies them one
    broadcast-GEMM at a time to bound executor memory; on TPU the blocks
    concatenate into one sharded GEMM (the MXU-friendly layout), while the
    per-block view is kept for API parity.
    """

    def __init__(
        self,
        block_weights: Sequence[np.ndarray],
        block_size: int,
        intercept: Optional[np.ndarray] = None,
        feature_means: Optional[np.ndarray] = None,
    ):
        self.block_weights = [np.asarray(w) for w in block_weights]
        self.block_size = block_size
        self.intercept = None if intercept is None else np.asarray(intercept)
        self.feature_means = (
            None if feature_means is None else np.asarray(feature_means)
        )
        self.weights = np.concatenate(self.block_weights, axis=0)

    def eq_key(self):
        return (
            BlockLinearMapper,
            self.block_size,
            self.weights.tobytes(),
            None if self.intercept is None else self.intercept.tobytes(),
            None if self.feature_means is None else self.feature_means.tobytes(),
        )

    def apply(self, x):
        if self.feature_means is not None:
            x = x - self.feature_means
        out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out


class BlockLeastSquaresEstimator(LabelEstimator):
    """The workhorse distributed solver (reference
    ``BlockLinearMapper.scala:196-257``): per-block mean-centering, label
    mean-centering, block coordinate descent with L2, intercept from the
    joint means. ``weight`` = 3*num_iter+1 passes over the data
    (reference :204) for the auto-cache planner.
    """

    def __init__(self, block_size: int, num_iter: int, lam: float = 0.0):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        n, d = ds.n, ds.data.shape[1]
        k = labels.data.shape[1]
        bs = self.block_size
        bounds = [(i, min(d, i + bs)) for i in range(0, d, bs)]

        X, Y = ds.data, labels.data
        x_mean = np.asarray(linalg.distributed_mean(X, n))
        y_mean = np.asarray(linalg.distributed_mean(Y, n))
        Ws = _block_solve(
            X,
            Y,
            jnp.asarray(x_mean),
            jnp.asarray(y_mean),
            ds.mask,
            float(self.lam),
            tuple(bounds),
            self.num_iter,
        )
        block_ws = [np.asarray(w) for w in Ws]
        W = np.concatenate(block_ws, axis=0)
        intercept = y_mean  # apply() centers x by the means, so b = y_mean
        return BlockLinearMapper(
            block_ws, bs, intercept=intercept, feature_means=x_mean
        )

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        """Reference cost model (BlockLinearMapper.scala:268-282)."""
        flops = n * d * (self.block_size + k) / num_machines
        bytes_scanned = n * d / num_machines + d * k
        network = 2.0 * (d * (self.block_size + k)) * np.log2(max(num_machines, 1))
        return self.num_iter * (
            max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
        )


@functools.partial(jax.jit, static_argnames=("bounds", "num_iter"))
def _block_solve(X, Y, x_mean, y_mean, mask, lam, bounds, num_iter):
    m = mask[:, None].astype(X.dtype)
    Yc = (Y - y_mean) * m
    blocks = [(X[:, lo:hi] - x_mean[lo:hi]) * m for lo, hi in bounds]
    return linalg.bcd_core(blocks, Yc, jnp.asarray(lam, X.dtype), num_passes=num_iter)
