"""Linear models and least-squares estimators.

TPU-native re-designs of reference ``nodes/learning/LinearMapper.scala``
and ``nodes/learning/BlockLinearMapper.scala`` (SURVEY.md section 2.3):
the Spark Gram-accumulate + driver-Cholesky becomes a sharded GEMM +
all-reduce + replicated Cholesky, and block coordinate descent runs as one
jitted program with per-block Gram psums.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import linalg
from ...parallel.dataset import ensure_array, ArrayDataset, Dataset
from ...parallel.mesh import replicated_zeros
from ...utils.donation import donating_jit
from ...workflow.label_estimator import LabelEstimator
from ...workflow.transformer import Transformer
from ..stats import StandardScalerModel


@jax.jit
def _moments3(a):
    # nan-ignoring moments + a non-finite count: finite arrays get the
    # plain moments (count 0); broken arrays stay distinguishable by
    # their finite content instead of collapsing to one NaN token
    a32 = a.astype(jnp.float32)
    finite = jnp.isfinite(a32)
    z = jnp.where(finite, a32, 0.0)
    return jnp.stack(
        [jnp.sum(z), jnp.sum(jnp.square(z)), jnp.sum(jnp.abs(z)),
         jnp.sum(~finite).astype(jnp.float32)])


def _array_token(a):
    """Device-cheap content identity for ``eq_key``: shape + dtype +
    three global moments (ONE dispatch, one 12-byte pull) instead of
    serializing the whole array — the default ``tobytes`` key would
    drag a fitted (d, C) model through d2h just to hash it during
    fusion/CSE, and three separate scalar pulls would pay three
    dev-tunnel round trips. A collision needs identical shape AND
    identical f32 sum / sum-of-squares / sum-of-abs; its only
    consequence is CSE or the fusion cache merging two
    indistinguishable models."""
    if a is None:
        return None
    arr = jnp.asarray(a)
    m = np.asarray(_moments3(arr))
    if m[3] != 0.0:
        # NaN would poison dict keys (NaN != NaN makes a fitted model
        # unequal to ITSELF, silently defeating CSE/fusion/jit caches
        # forever) — the nan-ignoring moments keep the key stable AND
        # content-distinguishing, and a non-finite fitted array is
        # worth shouting about: a silently-NaN solve predicts a
        # constant class. The warning also lands in the numerics event
        # funnel (metrics/trace/flight-recorder), so dashboards see it
        # even when nobody reads the log.
        import logging

        from ...observability.numerics import record_numerics_event

        record_numerics_event("nonfinite_model",
                              shape=tuple(arr.shape), count=int(m[3]))
        logging.getLogger(__name__).warning(
            "fitted array %s contains %d non-finite values — the solve "
            "likely failed; check conditioning/lambda",
            arr.shape, int(m[3]))
    return (arr.shape, str(arr.dtype),
            float(m[0]), float(m[1]), float(m[2]), float(m[3]))


# -- quantized predict (serving plane) -------------------------------------
#
# The PR 5 wire_dtype contract applied to WEIGHTS: a fitted mapper may
# hold its weight matrix at a narrower dtype than f32 — bf16, or int8
# with per-column scales — for the serving plane, where the apply path
# re-reads the full (d, k) matrix from HBM per request batch. Accuracy
# is policed two ways: the quantization error is recorded into the
# numerics funnel the moment the weights narrow (``numerics.quant_error``
# event + ``numerics.quant_rel_error`` gauge), and the parity gate
# (tools/profile_imagenet.py, tests/test_pallas_kernels.py) pins
# argmax agreement and an error bound against the f32 apply.

def _canon_weight_dtype(weight_dtype):
    if weight_dtype is None:
        return None
    alias = {"bf16": "bf16", "bfloat16": "bf16", "int8": "int8"}
    try:
        key = alias.get(str(np.dtype(weight_dtype)), None) \
            if not isinstance(weight_dtype, str) else alias.get(weight_dtype)
    except TypeError:
        key = alias.get(str(weight_dtype))
    if key is None:
        raise ValueError(
            f"weight_dtype must be None, 'bf16' or 'int8', got "
            f"{weight_dtype!r}")
    return key


def _quantize_weights(W, weight_dtype):
    """Quantize a fitted (d, k) f32 weight matrix: bf16 (scales of
    ones), or int8 with per-COLUMN scales (symmetric, 127 levels —
    each output class keeps its own dynamic range, so one large-norm
    column cannot crush the resolution of the rest). Returns
    ``(Wq, scale)`` and records the dequantization error into the
    numerics funnel — quantization drift is a numbers-plane event, not
    a silent precision choice."""
    from ...observability import MetricsRegistry
    from ...observability.numerics import record_numerics_event

    Wf = jnp.asarray(W, jnp.float32)
    k = Wf.shape[1]
    if weight_dtype == "bf16":
        Wq = Wf.astype(jnp.bfloat16)
        scale = jnp.ones((k,), jnp.float32)
    else:
        amax = jnp.max(jnp.abs(Wf), axis=0)
        scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
        Wq = jnp.clip(jnp.round(Wf / scale[None, :]), -127.0, 127.0) \
            .astype(jnp.int8)
    deq = Wq.astype(jnp.float32) * scale[None, :]
    denom = jnp.maximum(jnp.max(jnp.abs(Wf)), 1e-12)
    err = jnp.abs(deq - Wf)
    max_rel = float(jnp.max(err) / denom)
    rms_rel = float(jnp.sqrt(jnp.mean(err * err)) / denom)
    MetricsRegistry.get_or_create().gauge(
        "numerics.quant_rel_error").set(max_rel)
    record_numerics_event(
        "quant_error", dtype=weight_dtype, shape=tuple(Wf.shape),
        max_rel=round(max_rel, 6), rms_rel=round(rms_rel, 6))
    return Wq, scale


def _maybe_quantized_params(affine, weight_dtype):
    """The shared apply_params tail of both mappers: narrow the f32
    affine params when a weight_dtype is set (4-tuple stays the plain
    `_affine_apply_batch` contract; 5-tuple is the quantized one)."""
    if weight_dtype is None:
        return affine
    W, mean, inv_std, b = affine
    Wq, scale = _quantize_weights(W, weight_dtype)
    return (Wq, scale, mean, inv_std, b)


def _dequant_affine(params, x):
    """The ONE home of the dequantizing affine math — shared by the
    per-item apply, the fused-chain apply_with_params, and the batched
    program's einsum fallback, so the quantization semantics cannot
    silently diverge between paths."""
    Wq, scale, mean, inv_std, b = params
    return ((x - mean) * inv_std) @ (
        Wq.astype(jnp.float32) * scale[None, :]) + b


@jax.jit
def _quantized_affine_batch(X, Wq, scale, mean, inv_std, b):
    """Whole-batch quantized fitted-model apply, params as ARGUMENTS
    (the `_affine_apply_batch` contract — one compile serves every
    refit): ``((X - mean) * inv_std) @ dequant(Wq) + b`` with f32
    accumulation. Dispatch: the Pallas kernel on TPU when the
    VMEM-resident weight block fits (``ops.pallas_kernels.
    quantized_affine_pallas``), else the dequantizing einsum fallback
    (bit-compatible: same dequantize-then-f32-matmul math)."""
    from ...ops.pallas_kernels import (
        quant_fits_vmem,
        quantized_affine_pallas,
        use_pallas,
    )

    d, k = Wq.shape
    if use_pallas() and quant_fits_vmem(d, k, Wq.dtype.itemsize):
        return quantized_affine_pallas(X, Wq, scale, mean, inv_std, b)
    return _dequant_affine((Wq, scale, mean, inv_std, b), X)


class LinearMapper(Transformer):
    """out = x_model^T in (+ b), with optional feature scaler
    (reference ``LinearMapper.scala:18-62``). ``weight_dtype`` narrows
    the stored weights on the apply path (None = f32; ``"bf16"`` /
    ``"int8"`` per-column-scaled — the serving plane's quantized
    predict, see ``_quantize_weights``)."""

    def __init__(
        self,
        weights: np.ndarray,
        intercept: Optional[np.ndarray] = None,
        feature_scaler: Optional[StandardScalerModel] = None,
        weight_dtype: Optional[str] = None,
    ):
        # host or device arrays, kept as handed in (see BlockLinearMapper)
        self.weights = weights
        self.intercept = intercept
        self.feature_scaler = feature_scaler
        self.weight_dtype = _canon_weight_dtype(weight_dtype)
        if (self.weight_dtype is not None and feature_scaler is not None
                and type(feature_scaler) is not StandardScalerModel):
            raise ValueError(
                "weight_dtype quantization requires a plain "
                "StandardScalerModel feature scaler (or none): the "
                "quantized apply is one fused affine program")

    def __getstate__(self):
        d = super().__getstate__()  # strips per-instance jit caches
        d["weights"] = np.asarray(self.weights)
        if d["intercept"] is not None:
            d["intercept"] = np.asarray(d["intercept"])
        return d

    def eq_key(self):
        return (
            LinearMapper,
            self.weight_dtype,
            _array_token(self.weights),
            _array_token(self.intercept),
            None if self.feature_scaler is None
            else self.feature_scaler._cached_eq_key(),
        )

    def apply(self, x):
        if self.weight_dtype is not None:
            return self.apply_with_params(self.apply_params(), x)
        if self.feature_scaler is not None:
            x = self.feature_scaler.apply(x)
        out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def _simple_scaler(self):
        """The scaler when it is exactly a StandardScalerModel (the
        fitted shape); anything else keeps the default baked path."""
        from ..stats import StandardScalerModel

        s = self.feature_scaler
        return s if s is None or type(s) is StandardScalerModel else False

    def apply_dataset(self, ds: Dataset) -> Dataset:
        params = self.apply_params()
        if isinstance(ds, ArrayDataset) and params is not None:
            if self.weight_dtype is not None:
                return ds.map_batch(
                    lambda X: _quantized_affine_batch(X, *params))
            return ds.map_batch(
                lambda X: _affine_apply_batch(X, *params))
        return super().apply_dataset(ds)

    # fitted-param protocol: fused chains thread these as jit arguments
    fusion_safe = True

    def apply_params(self):
        scaler = self._simple_scaler()
        if scaler is False:
            return None  # arbitrary scaler node: baked/content-keyed path
        params = self.__dict__.get("_jit_affine_params")
        if params is None:
            mean = None if scaler is None else scaler.mean
            inv = (None if scaler is None or scaler.std is None
                   else 1.0 / np.asarray(scaler.std))
            params = _maybe_quantized_params(
                _affine_params(self.weights, mean, inv, self.intercept),
                self.weight_dtype)
            self.__dict__["_jit_affine_params"] = params  # _jit_*: unpickled
        return params

    def apply_with_params(self, params, x):
        if self.weight_dtype is not None:
            return _dequant_affine(params, x)
        W, mean, inv_std, b = params
        return ((x - mean) * inv_std) @ W + b

    def struct_key(self):
        if self._simple_scaler() is False:
            return super().struct_key()
        return (LinearMapper, "affine", self.weight_dtype)

    def sharded_apply_nbytes(self):
        """(shardable at rest, gather transient) under the spmd
        sharded apply — W row-shards, and the whole matrix gathers
        per call (the FSDP unit). Quantized mappers keep the fused
        dequant program with only the batch sharded: nothing shards
        at rest, nothing gathers."""
        if self.weight_dtype is not None:
            return 0.0, 0.0
        nb = float(self.weights.nbytes)
        return nb, nb


class LinearMapEstimator(LabelEstimator):
    """OLS/ridge via distributed normal equations on mean-centered features
    and labels; intercept = label mean (reference
    ``LinearMapper.scala:71-98``)."""

    def __init__(self, lam: Optional[float] = None,
                 weight_dtype: Optional[str] = None):
        self.lam = lam
        # serving-plane quantized predict: the fitted mapper narrows
        # its weights (validated eagerly so a typo fails at config
        # time, not after the fit)
        self.weight_dtype = _canon_weight_dtype(weight_dtype)

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    # -- static HBM planning (analysis.resources) --------------------------
    def carry_nbytes(self, dep_specs):
        from ...analysis.resources import gram_carry_nbytes

        return gram_carry_nbytes(dep_specs)

    def fitted_nbytes(self, dep_specs):
        from ...analysis.resources import linear_model_nbytes

        return linear_model_nbytes(dep_specs)

    # -- streaming fit (accumulate/finalize protocol) ----------------------
    def accumulate(self, carry, chunk, labels):
        """One chunk's contribution to the raw Gram/cross/sum carry (the
        fused ``gram_cross`` kernel streams each row tile through VMEM
        once). Padded chunk rows are zero, so sums stay exact."""
        return accumulate_gram_carry(carry, chunk, labels)

    def finalize(self, carry):
        """Centered ridge normal equations from the accumulated raw
        moments: Gc = G - n mu_x mu_x^T, Cc = C - n mu_x mu_y^T —
        algebraically identical to the resident ``_fit``, with only the
        carry (d x d + d x k) ever resident in HBM."""
        G, C, sx, sy, n = carry
        x_mean, y_mean, W = _finalize_normal_equations(
            G, C, sx, sy, jnp.asarray(n, G.dtype),
            jnp.asarray(float(self.lam or 0.0), G.dtype))
        return LinearMapper(
            W,
            intercept=y_mean,
            feature_scaler=StandardScalerModel(x_mean),
            weight_dtype=self.weight_dtype,
        )

    def _fit(self, ds: Dataset, labels: Dataset) -> LinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        n = ds.n
        X, Y = ds.data, labels.data
        # ONE dispatch for means + centering + normal equations: the
        # split form cost three jit round-trips per fit, which dominated
        # the measured solve time at small d (tools/calibrate_cost_model
        # finding, round 4) — on the tunneled bench chip 2 extra
        # dispatches cost more than the d=256 solve itself
        x_mean, y_mean, W = _means_and_normal_equations(
            X, Y, ds.mask, jnp.asarray(n, X.dtype),
            float(self.lam or 0.0))
        return LinearMapper(
            W,
            intercept=y_mean,
            feature_scaler=StandardScalerModel(x_mean),
            weight_dtype=self.weight_dtype,
        )

    #: Serial device round-trips per fit (center / gram / factorize /
    #: solve / intercept plus eigendecomposition host syncs), measured
    #: shape-independent at ~180 ms on the axon chip (r5 calibration:
    #: 184/163/198 ms across n=1k..65k at tiny compute).
    DISPATCH_ROUNDS = 10

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (LinearMapper.scala:100-115) extended
        with a dispatch-latency term (``lat_w`` seconds per serial
        device round): on TPU the compute terms alone mis-rank small-d
        solves, where per-round dispatch latency dominates (r5
        calibration, tools/calibrate_cost_model.py). ``lat_w=0``
        reproduces the reference surface exactly."""
        flops = n * d * (d + k) / num_machines
        bytes_scanned = n * d / num_machines + d * d
        network = d * (d + k)
        return (max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
                + lat_w * self.DISPATCH_ROUNDS)

    @staticmethod
    def compute_cost(
        ds,
        labels,
        lam: float,
        weights: np.ndarray,
        intercept: Optional[np.ndarray] = None,
    ) -> float:
        """Ridge objective at (weights, intercept): ||XW + b - L||_F^2 / (2n)
        + lam/2 ||W||_F^2 (reference ``LinearMapper.scala:124-161``,
        ``LinearMapEstimator.computeCost``). ``ds``/``labels`` may be
        Datasets or arrays; the residual reduction runs on device over the
        sharded batch."""
        ds, labels = ensure_array(ds), ensure_array(labels)
        b = (
            jnp.zeros((weights.shape[1],), jnp.float32)
            if intercept is None
            else jnp.asarray(intercept)
        )
        cost = _squared_residual_sum(
            ds.data, labels.data, jnp.asarray(weights), b, ds.mask
        )
        total = float(cost) / (2.0 * ds.n)
        if lam != 0.0:
            total += lam / 2.0 * float(np.sum(np.asarray(weights) ** 2))
        return total


@jax.jit
def _affine_apply_batch(X, W, mean, inv_std, b):
    """Whole-batch fitted-model apply with params as ARGUMENTS:
    ((X - mean) * inv_std) @ W + b. A jit built over ``self.apply``
    closes over the fitted arrays and bakes them into the HLO as
    constants, so every refit on new data produces a brand-new program
    (measured: the fitted model's batched apply was the ONLY program
    recompiling when app data changed — minutes per cold fit on the
    bench chip). With params as arguments the program is content-free:
    one compile serves every refit, in-process and via the persistent
    compilation cache."""
    return ((X - mean) * inv_std) @ W + b


def _affine_params(W, mean, inv_std, b):
    dt = jnp.float32
    Wd = jnp.asarray(W, dt)
    d, k = Wd.shape
    return (
        Wd,
        jnp.zeros((d,), dt) if mean is None else jnp.asarray(mean, dt),
        jnp.ones((d,), dt) if inv_std is None else jnp.asarray(inv_std, dt),
        jnp.zeros((k,), dt) if b is None else jnp.asarray(b, dt),
    )


# -- streaming carry (shared by the whole least-squares family) ------------
#
# The carry is the Spark analogue of per-partition Gram reduction
# (SURVEY.md section 3.2): raw second moments (G = X^T X, C = X^T Y) plus
# raw first moments (column sums) and the true row count. Centering is
# recovered at finalize time (Gc = G - n mu mu^T), so accumulation is a
# pure sum — chunk order cannot change the result beyond f32 rounding.


def _gram_carry_update_impl(G, C, sx, sy, X, Y):
    from ...ops.pallas_kernels import gram_cross

    X = X.astype(jnp.float32)
    Y = Y.astype(jnp.float32)
    g, c = gram_cross(X, Y)  # fused: one pass over the chunk's rows
    return (G + g, C + c,
            sx + jnp.sum(X, axis=0), sy + jnp.sum(Y, axis=0))


def _carry_probe(d: int = 8, k: int = 3, n: int = 16):
    """Tiny shape witness for the donation gate: every donated carry
    piece must have a shape-compatible output (checked abstractly by
    ``utils.donation.donation_shape_mismatches`` — see tools/lint.py)."""
    S, f32 = jax.ShapeDtypeStruct, np.float32
    return ((S((d, d), f32), S((d, k), f32), S((d,), f32), S((k,), f32),
             S((n, d), f32), S((n, k), f32)), {})


#: The per-chunk carry update DONATES the carry buffers (G, C, sx, sy):
#: XLA writes the updated carry into the old carry's HBM instead of
#: allocating a fresh (d, d) + (d, k) pair per chunk — a streamed fit
#: holds ONE carry, with zero per-chunk allocator traffic. The chunk
#: arrays (X, Y) are NOT donated: the prefetch buffer still owns them.
#: Callers must treat the passed-in carry as dead after the call
#: (``fit_streaming``'s loop reassigns immediately, and checkpointing
#: copies the carry to host BEFORE the next accumulate donates it).
_gram_carry_update = donating_jit(
    _gram_carry_update_impl, donate_argnums=(0, 1, 2, 3),
    probe=_carry_probe)


def accumulate_gram_carry(carry, chunk, labels):
    """Fold one (features, labels) chunk pair into the
    ``(G, C, sx, sy, n)`` carry (``n`` stays a host int — it is the only
    piece of the carry the driver loop reads). Chunks must be
    ArrayDatasets with the zero-pad invariant (StreamingDataset output
    or any masked resident dataset)."""
    chunk, labels = ensure_array(chunk), ensure_array(labels)
    X, Y = chunk.data, labels.data
    if X.ndim != 2 or Y.ndim != 2:
        raise ValueError(
            f"streamed least-squares needs 2-D (n, d)/(n, k) chunks, got "
            f"{X.shape} / {Y.shape}")
    if X.shape[0] != Y.shape[0]:
        raise ValueError(
            f"chunk/labels padded rows differ: {X.shape[0]} vs "
            f"{Y.shape[0]}")
    if carry is None:
        d, k = X.shape[1], Y.shape[1]
        # the zero carry is REPLICATED on the chunk's mesh explicitly:
        # a plain jnp.zeros is SingleDeviceSharding, and since jax's
        # jit cache keys on input shardings, chunk 2 (whose carry is
        # the mesh-sharded output of chunk 1's update) would recompile
        # _gram_carry_update once per fit — an ~80 ms chunk-2 stall the
        # compile observatory's fit fence flagged the moment it was
        # armed (PR 9); with a replicated init the output shardings are
        # stable from call 1 and the whole fit compiles exactly once
        carry = tuple(replicated_zeros(chunk.mesh, (
            (d, d), (d, k), (d,), (k,)))) + (0,)
    G, C, sx, sy, n = carry
    G, C, sx, sy = _gram_carry_update(G, C, sx, sy, X, Y)
    return (G, C, sx, sy, n + chunk.n)


def _finalize_normal_equations_impl(G, C, sx, sy, n, lam):
    with linalg.solver_precision():
        x_mean = sx / n
        y_mean = sy / n
        Gc = G - n * jnp.outer(x_mean, x_mean)
        Cc = C - n * jnp.outer(x_mean, y_mean)
        return x_mean, y_mean, linalg.ridge_cho_solve(
            Gc, Cc, lam, site="finalize_normal_equations")


def _finalize_probe(d: int = 8, k: int = 3):
    S, f32 = jax.ShapeDtypeStruct, np.float32
    return ((S((d, d), f32), S((d, k), f32), S((d,), f32), S((k,), f32),
             S((), f32), S((), f32)), {})


#: finalize consumes the carry: donate the pieces with a
#: SHAPE-COMPATIBLE output — C (d,k) -> W, sx -> x_mean, sy -> y_mean.
#: G (d,d) matches no output, so donating it cannot be honored and
#: would only emit jax's donated-buffer-not-usable warning per compile
#: on the backends where donation is real (pinned: the probe makes this
#: a static gate, tests/test_analysis_passes.py a no-warnings test).
_finalize_normal_equations = donating_jit(
    _finalize_normal_equations_impl, donate_argnums=(1, 2, 3),
    probe=_finalize_probe)


def _gram_bcd_impl(G, C, sx, sy, n, lam, bounds, num_iter):
    """Block coordinate descent driven entirely from the accumulated
    Gram/cross carry: the update

        W_b <- (Gc[b,b] + lam I)^-1 (Cc[b] - Gc[b,:] W + Gc[b,b] W_b)

    is algebraically the data-form update A_b^T (Yc - P + A_b W_b) of
    ``ops.linalg.bcd_core`` (same sequential block order, same per-block
    Cholesky reuse and breakdown recovery), so streamed and resident
    BlockLS fits agree to f32 rounding — without the (n, d) data ever
    being resident."""
    with linalg.solver_precision():
        dtype = G.dtype
        k = C.shape[1]
        x_mean = sx / n
        y_mean = sy / n
        Gc = G - n * jnp.outer(x_mean, x_mean)
        Cc = C - n * jnp.outer(x_mean, y_mean)
        factors, oks, ratios = [], [], []
        for lo, hi in bounds:
            Gb = Gc[lo:hi, lo:hi] + lam * jnp.eye(hi - lo, dtype=dtype)
            L = jax.scipy.linalg.cho_factor(Gb, lower=True)
            factors.append(L)
            ok, ratio = linalg._chol_health(L[0], Gb)
            oks.append(ok)
            ratios.append(ratio)
        # streamed BlockLS breakdowns land in the conditioning ledger
        # exactly like the resident BCD's (one callback, all blocks)
        from ...observability.numerics import record_block_health

        record_block_health("gram_bcd", jnp.stack(oks),
                            jnp.stack(ratios))
        W = jnp.zeros((G.shape[0], k), dtype)
        for _ in range(num_iter):
            for i, (lo, hi) in enumerate(bounds):
                rhs = (Cc[lo:hi] - Gc[lo:hi, :] @ W
                       + Gc[lo:hi, lo:hi] @ W[lo:hi])
                Wi = jax.scipy.linalg.cho_solve(factors[i], rhs)
                Wi = linalg._finite_or_eigh_solve(
                    Wi,
                    lambda lo=lo, hi=hi: Gc[lo:hi, lo:hi]
                    + lam * jnp.eye(hi - lo, dtype=dtype),
                    rhs, ok=oks[i])
                W = W.at[lo:hi].set(Wi)
        return tuple(W[lo:hi] for lo, hi in bounds), x_mean, y_mean


def _gram_bcd_probe(d: int = 8, k: int = 3):
    S, f32 = jax.ShapeDtypeStruct, np.float32
    return ((S((d, d), f32), S((d, k), f32), S((d,), f32), S((k,), f32),
             S((), f32), S((), f32)),
            {"bounds": ((0, 4), (4, 8)), "num_iter": 1})


#: the Gram-form BCD finalize donates the carry pieces XLA can actually
#: reuse: sx -> x_mean, sy -> y_mean. G (d,d) and C (d,k) match no
#: output (the weights come back as per-block slices), so donating them
#: would only trigger the not-usable warning — see
#: ``_finalize_normal_equations``.
_gram_bcd = donating_jit(
    _gram_bcd_impl, donate_argnums=(2, 3),
    static_argnames=("bounds", "num_iter"), probe=_gram_bcd_probe)


@jax.jit
def _centered_normal_equations(X, Y, x_mean, y_mean, mask, lam):
    m = mask[:, None].astype(X.dtype)
    Xc = (X - x_mean) * m
    Yc = (Y - y_mean) * m
    return linalg.ridge_cho_solve(linalg.gram(Xc), linalg.cross(Xc, Yc), lam)


@jax.jit
def _means_and_normal_equations(X, Y, mask, n, lam):
    """Column means + centered ridge normal equations as one program
    (one device dispatch per fit; see ``LinearMapEstimator._fit``)."""
    m = mask[:, None].astype(X.dtype)
    x_mean = jnp.sum(X * m, axis=0) / n
    y_mean = jnp.sum(Y * m, axis=0) / n
    W = _centered_normal_equations.__wrapped__(X, Y, x_mean, y_mean, mask, lam)
    return x_mean, y_mean, W


@jax.jit
def _masked_sse(pred, Y, b, mask):
    m = mask[:, None].astype(pred.dtype)
    resid = (pred + b - Y) * m
    return jnp.sum(resid * resid)


@jax.jit
def _squared_residual_sum(X, Y, W, b, mask):
    return _masked_sse(X @ W, Y, b, mask)


class BlockLinearMapper(Transformer):
    """Block-partitioned linear model (reference
    ``BlockLinearMapper.scala:22-73``).

    The reference stores ``Seq[DenseMatrix]`` blocks and applies them one
    broadcast-GEMM at a time to bound executor memory; on TPU the blocks
    concatenate into one sharded GEMM (the MXU-friendly layout), while the
    per-block view is kept for API parity.
    """

    def __init__(
        self,
        block_weights: Sequence[np.ndarray],
        block_size: int,
        intercept: Optional[np.ndarray] = None,
        feature_means: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
        weight_dtype: Optional[str] = None,
    ):
        # blocks are kept as handed in (host OR device arrays): forcing
        # np.asarray here would drag freshly-fitted device weights to
        # host — a multi-second d2h for ImageNet-scale (d x 1000)
        # models — only for apply() to ship them straight back.
        # ``weights`` lets a caller that already assembled the full
        # matrix skip the concat copy.
        self.block_weights = list(block_weights)
        self.block_size = block_size
        self.intercept = intercept
        self.feature_means = feature_means
        self.weight_dtype = _canon_weight_dtype(weight_dtype)
        if weights is not None:
            self.weights = weights
        else:
            concat = (
                jnp.concatenate
                if any(isinstance(w, jax.Array) for w in self.block_weights)
                else np.concatenate
            )
            self.weights = concat(self.block_weights, axis=0)

    def eq_key(self):
        return (
            BlockLinearMapper,
            self.block_size,
            self.weight_dtype,
            _array_token(self.weights),
            _array_token(self.intercept),
            _array_token(self.feature_means),
        )

    def __getstate__(self):
        # device arrays pickle as host copies (checkpoint/FittedPipeline
        # serialization); super() strips per-instance jit caches
        d = super().__getstate__()
        d["block_weights"] = [np.asarray(w) for w in self.block_weights]
        d["weights"] = np.asarray(self.weights)
        for f in ("intercept", "feature_means"):
            if d[f] is not None:
                d[f] = np.asarray(d[f])
        return d

    def apply(self, x):
        if self.weight_dtype is not None:
            return self.apply_with_params(self.apply_params(), x)
        if self.feature_means is not None:
            x = x - self.feature_means
        out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_dataset(self, ds: Dataset) -> Dataset:
        if isinstance(ds, ArrayDataset):
            params = self.apply_params()
            if self.weight_dtype is not None:
                return ds.map_batch(
                    lambda X: _quantized_affine_batch(X, *params))
            return ds.map_batch(
                lambda X: _affine_apply_batch(X, *params))
        return super().apply_dataset(ds)

    # fitted-param protocol: fused chains thread these as jit arguments
    fusion_safe = True

    def apply_params(self):
        params = self.__dict__.get("_jit_affine_params")
        if params is None:
            params = _maybe_quantized_params(
                _affine_params(self.weights, self.feature_means,
                               None, self.intercept),
                self.weight_dtype)
            self.__dict__["_jit_affine_params"] = params  # _jit_*: unpickled
        return params

    def apply_with_params(self, params, x):
        if self.weight_dtype is not None:
            return _dequant_affine(params, x)
        W, mean, inv_std, b = params
        return ((x - mean) * inv_std) @ W + b

    def struct_key(self):
        return (BlockLinearMapper, "affine", self.weight_dtype)

    def sharded_apply_nbytes(self):
        """(shardable at rest, gather transient) under the spmd
        sharded apply: every block row-shards, and the in-body gather
        reassembles ONE block at a time — the transient peak is the
        largest block, which is what lets a model whose total
        ``weights.nbytes`` exceeds a single host's budget still be
        admitted (the concatenated ``weights`` view is derived state
        the sharded apply never materializes)."""
        if self.weight_dtype is not None:
            return 0.0, 0.0
        # charge the concat view too: it shards right alongside the
        # blocks (fitted_model_nbytes counted it, so we must as well)
        total = float(self.weights.nbytes) + sum(
            float(w.nbytes) for w in self.block_weights)
        unit = max(float(w.nbytes) for w in self.block_weights)
        return total, unit

    def _block_bounds(self) -> List[tuple]:
        bounds, lo = [], 0
        for w in self.block_weights:
            bounds.append((lo, lo + w.shape[0]))
            lo += w.shape[0]
        return bounds

    def apply_and_evaluate(self, blocks, evaluator) -> None:
        """Incremental per-block evaluation (reference
        ``BlockLinearMapper.scala:105-142``): after adding feature block i's
        contribution, call ``evaluator`` on the running prediction (partial
        sum + intercept). Lets callers track test error as the block solve
        consumes features, without materializing all blocks at once.

        ``blocks`` is a sequence of per-block feature Datasets/arrays
        aligned with ``block_weights``; each is centered by its slice of
        ``feature_means``. The partial sums stay on device; only the
        evaluated copy is handed to the callback.
        """
        assert len(blocks) == len(self.block_weights)
        bounds = self._block_bounds()
        partial = None
        for (lo, hi), w, block in zip(bounds, self.block_weights, blocks):
            block = ensure_array(block)
            x = block.data
            if self.feature_means is not None:
                x = x - self.feature_means[lo:hi]
            contrib = x @ jnp.asarray(w)
            partial = contrib if partial is None else partial + contrib
            out = partial
            if self.intercept is not None:
                out = out + jnp.asarray(self.intercept)
            # Re-zero pad rows (centering/intercept made them nonzero) so
            # the emitted dataset keeps ArrayDataset's zero-pad invariant.
            out = out * block.mask[:, None].astype(out.dtype)
            evaluator(
                ArrayDataset(out, block.n, block.mesh, _already_sharded=True)
            )


class BlockLeastSquaresEstimator(LabelEstimator):
    """The workhorse distributed solver (reference
    ``BlockLinearMapper.scala:196-257``): per-block mean-centering, label
    mean-centering, block coordinate descent with L2, intercept from the
    joint means. ``weight`` = 3*num_iter+1 passes over the data
    (reference :204) for the auto-cache planner.
    """

    def __init__(self, block_size: int, num_iter: int, lam: float = 0.0,
                 weight_dtype: Optional[str] = None):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.weight_dtype = _canon_weight_dtype(weight_dtype)

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    # -- static HBM planning (analysis.resources) --------------------------
    def carry_nbytes(self, dep_specs):
        from ...analysis.resources import gram_carry_nbytes

        return gram_carry_nbytes(dep_specs)

    def fitted_nbytes(self, dep_specs):
        from ...analysis.resources import linear_model_nbytes

        return linear_model_nbytes(dep_specs)

    # -- streaming fit (accumulate/finalize protocol) ----------------------
    def accumulate(self, carry, chunk, labels):
        """Same carry as the exact solver: raw Gram + cross + sums. Note
        the carry is (d, d) — streaming bounds HBM in ``n`` (the usual
        out-of-core axis: n >> d), not in ``d``."""
        return accumulate_gram_carry(carry, chunk, labels)

    def finalize(self, carry):
        G, C, sx, sy, n = carry
        d = G.shape[0]
        bs = self.block_size
        bounds = tuple((i, min(d, i + bs)) for i in range(0, d, bs))
        Ws, x_mean, y_mean = _gram_bcd(
            G, C, sx, sy, jnp.asarray(n, G.dtype),
            jnp.asarray(float(self.lam), G.dtype), bounds, self.num_iter)
        return BlockLinearMapper(
            list(Ws), bs, intercept=y_mean, feature_means=x_mean,
            weight_dtype=self.weight_dtype)

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        n, d = ds.n, ds.data.shape[1]
        k = labels.data.shape[1]
        bs = self.block_size
        bounds = [(i, min(d, i + bs)) for i in range(0, d, bs)]

        Ws, x_mean, y_mean = block_least_squares(
            ds.data, labels.data, n, float(self.lam), tuple(bounds),
            self.num_iter, mask=ds.mask)
        # blocks stay device-resident (see BlockLinearMapper.__init__)
        intercept = y_mean  # apply() centers x by the means, so b = y_mean
        return BlockLinearMapper(
            list(Ws), bs, intercept=intercept, feature_means=x_mean,
            weight_dtype=self.weight_dtype,
        )

    #: The scan-based BCD stages the whole multi-pass solve into ONE
    #: program (ops/linalg.py), so rounds do not scale with
    #: num_iter x num_blocks: measured ~51-65 ms fixed on the axon chip
    #: at 1..4 blocks x 3 passes (r5 calibration).
    DISPATCH_ROUNDS = 3

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (BlockLinearMapper.scala:268-282) plus
        the TPU dispatch-latency term (see ``LinearMapEstimator.cost``);
        ``lat_w=0`` reproduces the reference surface exactly."""
        flops = n * d * (self.block_size + k) / num_machines
        bytes_scanned = n * d / num_machines + d * k
        network = 2.0 * (d * (self.block_size + k)) * np.log2(max(num_machines, 1))
        return self.num_iter * (
            max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
        ) + lat_w * self.DISPATCH_ROUNDS

    @staticmethod
    def compute_cost(
        blocks,
        labels,
        lam: float,
        block_weights: Sequence[np.ndarray],
        intercept: Optional[np.ndarray] = None,
    ) -> float:
        """Training objective for a block model (reference
        ``BlockLinearMapper.scala:144-187`` ``computeCost``):
        ||sum_i X_i W_i + b - L||_F^2 / (2n) + lam/2 * sum_i ||W_i||_F^2.
        ``blocks`` holds the per-block features (Datasets or arrays)."""
        blocks = list(blocks)
        assert blocks and len(blocks) == len(block_weights), (
            f"{len(blocks)} feature blocks vs {len(block_weights)} weight blocks"
        )
        labels = ensure_array(labels)
        partial = None
        for w, block in zip(block_weights, blocks):
            block = ensure_array(block)
            contrib = block.data @ jnp.asarray(w)
            partial = contrib if partial is None else partial + contrib
        b = (
            jnp.zeros((labels.data.shape[1],), jnp.float32)
            if intercept is None
            else jnp.asarray(intercept)
        )
        cost = float(
            _masked_sse(partial, labels.data, b, labels.mask)
        ) / (2.0 * labels.n)
        if lam != 0.0:
            cost += lam / 2.0 * float(
                sum(np.sum(np.asarray(w) ** 2) for w in block_weights)
            )
        return cost


@functools.lru_cache(maxsize=None)
def _block_solve_for(mesh):
    """Jitted block solve, one trace cache per mesh (the
    ``_bcd_jit_for`` discipline): ``bcd_core`` reads the ambient mesh
    through ``_class_spec``, so a module-lifetime jit here baked the
    FIRST mesh's class-sharding constraints into the cached trace and
    silently replayed them under a second mesh at the same shapes —
    the dryrun_multichip(8) weighted-solver phase failure recorded in
    MULTICHIP_r06 (an 8-device sharding constraint against 1-device
    arguments). The mesh parameter keys the cache; the caller passes
    the ambient mesh so each mesh gets its own trace. The cross-module
    ``mesh-closure-jit`` lint (analysis/diagnostics.py) now flags the
    old shape statically."""

    @functools.partial(jax.jit, static_argnames=("bounds", "num_iter"))
    def _block_solve(X, Y, x_mean, y_mean, mask, lam, bounds, num_iter):
        m = mask[:, None].astype(X.dtype)
        Yc = (Y - y_mean) * m
        blocks = [(X[:, lo:hi] - x_mean[lo:hi]) * m for lo, hi in bounds]
        return linalg.bcd_core(blocks, Yc, jnp.asarray(lam, X.dtype),
                               num_passes=num_iter)

    return _block_solve


def block_least_squares(X, Y, n, lam, bounds, num_iter, mask=None):
    """Staged, jittable core of ``BlockLeastSquaresEstimator``: sharded
    column means + mean-centered block coordinate descent. Returns
    ``(per-block weights, x_mean, y_mean)``; prediction is
    ``(x - x_mean) @ concat(Ws) + y_mean``. The estimator's ``_fit``
    routes through this, so callers that stage the solve into a larger
    jit (e.g. bench.py's end-to-end program) time exactly the
    production solver path."""
    from ...parallel.mesh import get_mesh

    if mask is None:
        mask = jnp.ones(X.shape[0], X.dtype)
    x_mean = linalg.distributed_mean(X, n)
    y_mean = linalg.distributed_mean(Y, n)
    solve = _block_solve_for(get_mesh())
    return (
        solve(X, Y, x_mean, y_mean, mask, lam, bounds, num_iter),
        x_mean,
        y_mean,
    )
