"""Learning nodes: solvers and models (reference ``nodes/learning``,
SURVEY.md section 2.3)."""
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)

__all__ = [
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
]
