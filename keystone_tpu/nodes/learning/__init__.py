"""Learning nodes: solvers and models (reference ``nodes/learning``,
SURVEY.md section 2.3)."""
from .classifiers import (
    LinearDiscriminantAnalysis,
    LocalLeastSquaresEstimator,
    LogisticRegressionEstimator,
    LogisticRegressionModel,
    NaiveBayesEstimator,
    NaiveBayesModel,
    SparseLinearMapper,
)
from .gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    KMEANS_PLUS_PLUS_INITIALIZATION,
    RANDOM_INITIALIZATION,
)
from .kmeans import KMeansModel, KMeansPlusPlusEstimator
from .lbfgs import DenseLBFGSwithL2
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)
from .pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from .zca import ZCAWhitener, ZCAWhitenerEstimator

__all__ = [
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
    "DenseLBFGSwithL2",
    "KMeansModel",
    "KMeansPlusPlusEstimator",
    "GaussianMixtureModel",
    "GaussianMixtureModelEstimator",
    "KMEANS_PLUS_PLUS_INITIALIZATION",
    "RANDOM_INITIALIZATION",
    "PCAEstimator",
    "PCATransformer",
    "BatchPCATransformer",
    "ColumnPCAEstimator",
    "LocalColumnPCAEstimator",
    "DistributedColumnPCAEstimator",
    "DistributedPCAEstimator",
    "ApproximatePCAEstimator",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
    "NaiveBayesEstimator",
    "NaiveBayesModel",
    "LogisticRegressionEstimator",
    "LogisticRegressionModel",
    "LinearDiscriminantAnalysis",
    "LocalLeastSquaresEstimator",
    "SparseLinearMapper",
]

from .lbfgs import SparseLBFGSwithL2  # noqa: E402
from .least_squares import LeastSquaresEstimator  # noqa: E402
from .block_weighted import BlockWeightedLeastSquaresEstimator  # noqa: E402
from .per_class_weighted import (  # noqa: E402
    PerClassWeightedLeastSquaresEstimator,
)

__all__ += ["SparseLBFGSwithL2", "LeastSquaresEstimator",
            "BlockWeightedLeastSquaresEstimator",
            "PerClassWeightedLeastSquaresEstimator"]
