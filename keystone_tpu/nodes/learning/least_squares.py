"""Cost-model-driven least squares auto-solver (reference
``nodes/learning/LeastSquaresEstimator.scala``).

The flagship node-level optimization: choose among DenseLBFGS,
Sparsify -> SparseLBFGS, Densify -> BlockLeastSquares(1000, 3), and
Densify -> exact normal equations by evaluating each solver's cost model
at the observed workload shape (n, d, k, sparsity, num_machines).

The DEFAULT weights are TPU-calibrated on the bench chip (r5,
``tools/calibrate_cost_model.py``): seconds per solver-precision MXU
flop (floor-cancelled HIGHEST-gram rate), seconds per f32 element
streamed from HBM (floor-cancelled reduction), seconds per f32 element
over ICI (spec-derived; only matters multi-chip), and — the TPU-first
extension — seconds per serial device dispatch round (``lat_w``). The
latency term exists because on TPU the compute terms alone mis-rank
every small-d solve: measured end-to-end, BlockLS(1000,3) beats the
exact solver at (65536, 256) 38 ms vs 198 ms purely on dispatch
structure (the scan-based BCD is ONE program; the exact path is ~10
serial rounds), which no (cpu, mem) pair can express.

The reference's empirical calibration on 16x r3.4xlarge
(``LeastSquaresEstimator.scala:17,26-31``) is kept as
``REFERENCE_EC2_WEIGHTS`` for parity experiments; with those weights
and ``lat_weight=0`` the choice surface is the reference's exactly.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.optimizable import NodeChoice, OptimizableLabelEstimator
from ..util import Densify
from ..util.sparse import SparseVector, Sparsify
from .lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2
from .linear import BlockLeastSquaresEstimator, LinearMapEstimator

#: TPU-calibrated (r5 bench chip, TPU v5 lite behind the axon tunnel;
#: ship block printed by ``python tools/calibrate_cost_model.py``,
#: 2026-07-31, model-vs-measurement agreement 3/3 shapes). cpu:
#: floor-cancelled HIGHEST-precision gram rate; mem: floor-cancelled
#: HBM reduction stream; net: ICI spec; lat: measured per-dispatch-
#: round latency. The tunnel puts real run-to-run variance on the cpu/
#: mem primitive rates (the ranking is robust to it — the choice
#: surface at solver shapes is dominated by the lat and mem terms);
#: re-run the tool on other deployments.
DEFAULT_CPU_WEIGHT = 5.090e-15
DEFAULT_MEM_WEIGHT = 3.543e-11
DEFAULT_NETWORK_WEIGHT = 4.0e-11
DEFAULT_LAT_WEIGHT = 1.442e-2

#: The reference's EC2 calibration (LeastSquaresEstimator.scala:17,
#: 26-31) — documented fallback, not the default: it encodes a 2015
#: CPU-cluster cost surface.
REFERENCE_EC2_WEIGHTS = {
    "cpu_weight": 3.8e-4,
    "mem_weight": 2.9e-1,
    "network_weight": 1.32,
    "lat_weight": 0.0,
}


def estimate_sparsity(sample: Dataset) -> float:
    """Mean fraction of active entries per item
    (reference ``LeastSquaresEstimator.scala:68``)."""
    items = sample.collect() if not isinstance(sample, ArrayDataset) else None
    if items is not None:
        fracs = []
        for it in items:
            if isinstance(it, SparseVector):
                fracs.append(it.nnz / max(it.size, 1))
            else:
                arr = np.asarray(it)
                fracs.append(np.count_nonzero(arr) / max(arr.size, 1))
        return float(np.mean(fracs)) if fracs else 1.0
    arr = np.asarray(sample.numpy())
    return float(np.count_nonzero(arr) / max(arr.size, 1))


def _item_dim(sample: Dataset) -> int:
    if isinstance(sample, ArrayDataset):
        return int(np.asarray(
            __import__("jax").tree_util.tree_leaves(sample.data)[0]
        ).shape[-1])
    first = sample.collect()[0]
    return first.size if isinstance(first, SparseVector) else int(
        np.asarray(first).shape[-1])


class LeastSquaresEstimator(OptimizableLabelEstimator):
    """Auto-selecting least-squares solver
    (reference ``LeastSquaresEstimator.scala:27-86``)."""

    def __init__(
        self,
        lam: float = 0.0,
        num_machines: Optional[int] = None,
        cpu_weight: float = DEFAULT_CPU_WEIGHT,
        mem_weight: float = DEFAULT_MEM_WEIGHT,
        network_weight: float = DEFAULT_NETWORK_WEIGHT,
        num_iterations: int = 20,
        lat_weight: float = DEFAULT_LAT_WEIGHT,
    ):
        self.lam = lam
        self.num_machines = num_machines
        self.cpu_weight = cpu_weight
        self.mem_weight = mem_weight
        self.network_weight = network_weight
        self.num_iterations = num_iterations
        self.lat_weight = lat_weight

    @property
    def options(self) -> Sequence[Tuple[object, NodeChoice]]:
        """(cost-model solver, choice) pairs
        (reference ``LeastSquaresEstimator.scala:36-53``)."""
        dense = DenseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)
        sparse = SparseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)
        block = BlockLeastSquaresEstimator(1000, 3, lam=self.lam)
        exact = LinearMapEstimator(lam=self.lam)
        return [
            (dense, NodeChoice(dense, (Densify(),))),
            (sparse, NodeChoice(sparse, (Sparsify(),))),
            (block, NodeChoice(block, (Densify(),))),
            (exact, NodeChoice(exact, (Densify(),))),
        ]

    @property
    def default(self):
        return DenseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)

    @property
    def weight(self) -> int:
        return self.default.weight

    def _fit(self, ds: Dataset, labels: Dataset):
        # fallback path when the node-level optimizer has not sampled:
        # densify host sparse data for the dense default
        if not isinstance(ds, ArrayDataset):
            ds = Densify().apply_dataset(ds)
        if not isinstance(labels, ArrayDataset):
            labels = Densify().apply_dataset(labels)
        return self.default._fit(ds, labels)

    def optimize(self, sample: Dataset, sample_labels: Dataset, n: int,
                 num_machines: int) -> NodeChoice:
        d = _item_dim(sample)
        k = _item_dim(sample_labels)
        sparsity = estimate_sparsity(sample)
        machines = self.num_machines or num_machines
        costs = [
            (solver.cost(n, d, k, sparsity, machines, self.cpu_weight,
                         self.mem_weight, self.network_weight,
                         lat_w=self.lat_weight), i)
            for i, (solver, _) in enumerate(self.options)
        ]
        _, best = min(costs)
        return self.options[best][1]
