"""Cost-model-driven least squares auto-solver (reference
``nodes/learning/LeastSquaresEstimator.scala``).

The flagship node-level optimization: choose among DenseLBFGS,
Sparsify -> SparseLBFGS, Densify -> BlockLeastSquares(1000, 3), and
Densify -> exact normal equations by evaluating each solver's cost model
at the observed workload shape (n, d, k, sparsity, num_machines).

The DEFAULT weights are TPU-calibrated on the bench chip (r5,
``tools/calibrate_cost_model.py``): seconds per solver-precision MXU
flop (floor-cancelled HIGHEST-gram rate), seconds per f32 element
streamed from HBM (floor-cancelled reduction), seconds per f32 element
over ICI (spec-derived; only matters multi-chip), and — the TPU-first
extension — seconds per serial device dispatch round (``lat_w``). The
latency term exists because on TPU the compute terms alone mis-rank
every small-d solve: measured end-to-end, BlockLS(1000,3) beats the
exact solver at (65536, 256) 38 ms vs 198 ms purely on dispatch
structure (the scan-based BCD is ONE program; the exact path is ~10
serial rounds), which no (cpu, mem) pair can express.

The reference's empirical calibration on 16x r3.4xlarge
(``LeastSquaresEstimator.scala:17,26-31``) is kept as
``REFERENCE_EC2_WEIGHTS`` for parity experiments; with those weights
and ``lat_weight=0`` the choice surface is the reference's exactly.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ...observability.trace import current_trace
from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.optimizable import NodeChoice, OptimizableLabelEstimator
from ..util import Densify
from ..util.sparse import SparseVector, Sparsify
from .lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2
from .linear import BlockLeastSquaresEstimator, LinearMapEstimator

#: TPU-calibrated (r5 bench chip, TPU v5 lite behind the axon tunnel;
#: ship block printed by ``python tools/calibrate_cost_model.py``,
#: 2026-07-31, model-vs-measurement agreement 3/3 shapes). cpu:
#: floor-cancelled HIGHEST-precision gram rate; mem: floor-cancelled
#: HBM reduction stream; net: ICI spec; lat: measured per-dispatch-
#: round latency.
#:
#: These shipped values are AXON-TUNNEL-INCLUSIVE: they were measured
#: through the dev tunnel, whose ~18-20 ms dispatch floor dominates
#: ``DEFAULT_LAT_WEIGHT`` in particular. On a deployment without the
#: tunnel, per-dispatch latency is orders of magnitude smaller, so
#: these defaults can over-prefer few-dispatch solvers (e.g. BlockLS)
#: — they are the *fallback*, not ground truth. Run
#: ``python tools/calibrate_cost_model.py`` on the target deployment;
#: it writes a calibration artifact (JSON with timestamp + hostname)
#: that this module loads in preference to the shipped values (see
#: :func:`load_calibration`), and whose provenance the observability
#: layer reports with every solver decision.
DEFAULT_CPU_WEIGHT = 5.090e-15
DEFAULT_MEM_WEIGHT = 3.543e-11
DEFAULT_NETWORK_WEIGHT = 4.0e-11
DEFAULT_LAT_WEIGHT = 1.442e-2

#: Where ``tools/calibrate_cost_model.py`` writes its artifact and where
#: :func:`load_calibration` looks by default; override with the
#: ``KEYSTONE_COST_CALIBRATION`` environment variable.
CALIBRATION_ENV = "KEYSTONE_COST_CALIBRATION"
DEFAULT_CALIBRATION_PATH = os.path.join(
    os.path.expanduser("~"), ".keystone_tpu", "cost_model_calibration.json")

_WEIGHT_KEYS = ("cpu_weight", "mem_weight", "network_weight", "lat_weight")

#: resolved-path -> (weights, provenance); the artifact is tiny but read
#: once per estimator construction otherwise
_CALIBRATION_CACHE: Dict[str, Tuple[Dict[str, float], Dict]] = {}


def _shipped_weights() -> Dict[str, float]:
    return {
        "cpu_weight": DEFAULT_CPU_WEIGHT,
        "mem_weight": DEFAULT_MEM_WEIGHT,
        "network_weight": DEFAULT_NETWORK_WEIGHT,
        "lat_weight": DEFAULT_LAT_WEIGHT,
    }


def load_calibration(
        path: Optional[str] = None,
) -> Tuple[Dict[str, float], Dict]:
    """Resolve the cost-model weights and their provenance.

    Returns ``(weights, provenance)`` where weights come from the
    calibration artifact written by ``tools/calibrate_cost_model.py``
    when one is present and valid (all four weights finite, compute
    weights positive), and otherwise fall back to the shipped
    tunnel-inclusive ``DEFAULT_*`` values. ``provenance`` carries
    ``source`` (``"artifact"`` / ``"shipped_defaults"``) plus the
    artifact's timestamp/hostname/device so trace consumers can judge
    whether the weights match the deployment that produced a decision.
    """
    candidate = (path or os.environ.get(CALIBRATION_ENV)
                 or DEFAULT_CALIBRATION_PATH)
    cached = _CALIBRATION_CACHE.get(candidate)
    if cached is not None:
        return cached
    weights = _shipped_weights()
    provenance: Dict = {
        "source": "shipped_defaults",
        "note": ("r5 bench-chip calibration, axon-tunnel-inclusive "
                 "(lat_weight carries the ~20 ms tunnel dispatch floor); "
                 "run tools/calibrate_cost_model.py on this deployment"),
    }
    try:
        with open(candidate) as f:
            blob = json.load(f)
        parsed = {k: float(blob[k]) for k in _WEIGHT_KEYS}
        ok = all(np.isfinite(v) for v in parsed.values()) and all(
            parsed[k] > 0 for k in ("cpu_weight", "mem_weight",
                                    "network_weight")
        ) and parsed["lat_weight"] >= 0
        # the tool refuses to write low-agreement artifacts, but guard
        # against hand-made / older ones: weights whose recorded
        # model-vs-measurement agreement was <= half are not trustworthy
        agreement = str(blob.get("agreement", ""))
        if ok and "/" in agreement:
            try:
                hits, total = (int(p) for p in agreement.split("/", 1))
                ok = 2 * hits > total
            except ValueError:
                pass
        if ok:
            weights = parsed
            provenance = {
                "source": "artifact",
                "path": candidate,
                "timestamp": blob.get("timestamp"),
                "hostname": blob.get("hostname"),
                "device": blob.get("device"),
            }
        else:
            provenance["note"] = (
                f"calibration artifact {candidate} has out-of-range "
                "weights; using shipped defaults")
    except FileNotFoundError:
        pass
    except Exception as exc:  # malformed artifact: fall back loudly
        provenance["note"] = (
            f"calibration artifact {candidate} unreadable ({exc}); "
            "using shipped defaults")
    _CALIBRATION_CACHE[candidate] = (weights, provenance)
    return weights, provenance


def clear_calibration_cache() -> None:
    """Drop memoized calibration lookups (tests, recalibration)."""
    _CALIBRATION_CACHE.clear()

#: The reference's EC2 calibration (LeastSquaresEstimator.scala:17,
#: 26-31) — documented fallback, not the default: it encodes a 2015
#: CPU-cluster cost surface.
REFERENCE_EC2_WEIGHTS = {
    "cpu_weight": 3.8e-4,
    "mem_weight": 2.9e-1,
    "network_weight": 1.32,
    "lat_weight": 0.0,
}


def estimate_sparsity(sample: Dataset) -> float:
    """Mean fraction of active entries per item
    (reference ``LeastSquaresEstimator.scala:68``)."""
    items = sample.collect() if not isinstance(sample, ArrayDataset) else None
    if items is not None:
        fracs = []
        for it in items:
            if isinstance(it, SparseVector):
                fracs.append(it.nnz / max(it.size, 1))
            else:
                arr = np.asarray(it)
                fracs.append(np.count_nonzero(arr) / max(arr.size, 1))
        return float(np.mean(fracs)) if fracs else 1.0
    arr = np.asarray(sample.numpy())
    return float(np.count_nonzero(arr) / max(arr.size, 1))


def _item_dim(sample: Dataset) -> int:
    if isinstance(sample, ArrayDataset):
        return int(np.asarray(
            __import__("jax").tree_util.tree_leaves(sample.data)[0]
        ).shape[-1])
    first = sample.collect()[0]
    return first.size if isinstance(first, SparseVector) else int(
        np.asarray(first).shape[-1])


class LeastSquaresEstimator(OptimizableLabelEstimator):
    """Auto-selecting least-squares solver
    (reference ``LeastSquaresEstimator.scala:27-86``)."""

    def __init__(
        self,
        lam: float = 0.0,
        num_machines: Optional[int] = None,
        cpu_weight: Optional[float] = None,
        mem_weight: Optional[float] = None,
        network_weight: Optional[float] = None,
        num_iterations: int = 20,
        lat_weight: Optional[float] = None,
    ):
        # weights default to the per-host calibration artifact when one
        # exists, else the shipped tunnel-inclusive defaults; explicit
        # arguments always win (and mark provenance as "explicit")
        calibrated, provenance = load_calibration()
        explicit = {
            "cpu_weight": cpu_weight,
            "mem_weight": mem_weight,
            "network_weight": network_weight,
            "lat_weight": lat_weight,
        }
        if any(v is not None for v in explicit.values()):
            provenance = {"source": "explicit", "overrides": sorted(
                k for k, v in explicit.items() if v is not None)}
        self.lam = lam
        self.num_machines = num_machines
        self.cpu_weight = (cpu_weight if cpu_weight is not None
                           else calibrated["cpu_weight"])
        self.mem_weight = (mem_weight if mem_weight is not None
                           else calibrated["mem_weight"])
        self.network_weight = (network_weight if network_weight is not None
                               else calibrated["network_weight"])
        self.num_iterations = num_iterations
        self.lat_weight = (lat_weight if lat_weight is not None
                           else calibrated["lat_weight"])
        self._weight_provenance = provenance  # underscore: not in eq_key

    @property
    def options(self) -> Sequence[Tuple[object, NodeChoice]]:
        """(cost-model solver, choice) pairs
        (reference ``LeastSquaresEstimator.scala:36-53``)."""
        dense = DenseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)
        sparse = SparseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)
        block = BlockLeastSquaresEstimator(1000, 3, lam=self.lam)
        exact = LinearMapEstimator(lam=self.lam)
        return [
            (dense, NodeChoice(dense, (Densify(),))),
            (sparse, NodeChoice(sparse, (Sparsify(),))),
            (block, NodeChoice(block, (Densify(),))),
            (exact, NodeChoice(exact, (Densify(),))),
        ]

    @property
    def default(self):
        return DenseLBFGSwithL2(
            lam=self.lam, num_iterations=self.num_iterations)

    @property
    def weight(self) -> int:
        return self.default.weight

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    # -- static HBM planning (analysis.resources) --------------------------
    def carry_nbytes(self, dep_specs):
        # every gram-capable candidate finalizes from the one shared
        # Gram/cross carry, so the carry bound is solver-independent
        from ...analysis.resources import gram_carry_nbytes

        return gram_carry_nbytes(dep_specs)

    def fitted_nbytes(self, dep_specs):
        from ...analysis.resources import linear_model_nbytes

        return linear_model_nbytes(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset):
        # fallback path when the node-level optimizer has not sampled:
        # densify host sparse data for the dense default
        if not isinstance(ds, ArrayDataset):
            ds = Densify().apply_dataset(ds)
        if not isinstance(labels, ArrayDataset):
            labels = Densify().apply_dataset(labels)
        return self.default._fit(ds, labels)

    # -- streaming fit (accumulate/finalize protocol) ----------------------
    def accumulate(self, carry, chunk, labels):
        """Streamed fits share the linear family's Gram/cross carry;
        every Gram-capable candidate solver can finalize from it, so the
        solver choice is deferred to :meth:`finalize` (where n, d, k are
        all known exactly — no sampling, no extra pass)."""
        from .linear import accumulate_gram_carry

        return accumulate_gram_carry(carry, chunk, labels)

    def finalize(self, carry):
        """Cost-model choice over the GRAM-CAPABLE solvers at the exact
        accumulated workload shape, via the SAME ``_choose`` surface the
        optimizer uses (``streaming=True`` filters ``self.options`` to
        solvers that can finalize from the one-pass carry — the LBFGS
        candidates need per-pass data access a stream cannot provide).
        The decision rides the active trace with ``shape_source:
        "streamed"`` and ``streaming_restricted: true``."""
        from ...parallel.distributed import process_count
        from ...parallel.mesh import get_mesh, num_data_shards

        G, C, _, _, n = carry
        d, k = int(G.shape[0]), int(C.shape[1])
        # same machine count the static/sampled optimizer paths use —
        # the cost surface must not shift between a streamed fit and a
        # graph-optimized fit of the identical workload. Under a live
        # multi-process world the workload really is spread over
        # nproc x local shards (each host accumulated its shard-local
        # stream), so the cost surface sees the GLOBAL machine count —
        # every host computes the same number and makes the same choice.
        machines = self.num_machines or (
            num_data_shards(get_mesh()) * process_count())
        choice = self._choose(n, d, k, 1.0, machines,
                              "streamed", streaming=True)
        return choice.node.finalize(carry)

    def optimize(self, sample: Dataset, sample_labels: Dataset, n: int,
                 num_machines: int) -> NodeChoice:
        d = _item_dim(sample)
        k = _item_dim(sample_labels)
        sparsity = estimate_sparsity(sample)
        return self._choose(n, d, k, sparsity,
                            self.num_machines or num_machines, "sampled")

    def optimize_static(self, spec, n: int, num_machines: int,
                        labels_spec=None) -> Optional[NodeChoice]:
        """Cost-model choice from statically inferred (n, d, k, sparsity)
        — no sampled execution, no device time. ``sparsity`` here is the
        analyzer's STRUCTURAL density (1.0 for dense-stored elements),
        not the sampled value-level density ``estimate_sparsity``
        measures; solvers for dense-stored data are ranked as dense.
        Declines (returns None -> sampling fallback) when any cost input
        is unresolved, e.g. sparse host elements of unknown density."""
        from ...analysis.spec import element_feature_dim

        d = element_feature_dim(spec)
        k = element_feature_dim(labels_spec) if labels_spec is not None \
            else None
        sparsity = getattr(spec, "sparsity", None)
        if d is None or k is None or sparsity is None:
            return None
        return self._choose(n, d, k, sparsity,
                            self.num_machines or num_machines, "static",
                            streaming=getattr(spec, "streaming", False))

    def _choose(self, n: int, d: int, k: int, sparsity: float,
                machines: int, shape_source: str,
                streaming: bool = False) -> NodeChoice:
        """``streaming=True`` restricts the surface to solvers that can
        fit from the one-pass Gram/cross carry (exact, BlockLS): the
        LBFGS candidates need repeated data passes a stream cannot
        provide, and the Sparsify prefix is a host stage — choosing
        either for a StreamingDataset would fail (or materialize) at
        fit time."""
        from ...parallel.streaming import is_streamable

        options = self.options
        if streaming:
            options = [(solver, choice) for solver, choice in options
                       if is_streamable(choice.node)]
        costs = [
            (solver.cost(n, d, k, sparsity, machines, self.cpu_weight,
                         self.mem_weight, self.network_weight,
                         lat_w=self.lat_weight), i)
            for i, (solver, _) in enumerate(options)
        ]
        _, best = min(costs)
        choice = options[best][1]
        trace = current_trace()
        if trace is not None:
            # the full decision surface: workload shape, every candidate's
            # cost estimate, the pick, where the weights came from, and
            # whether the shape was sampled or statically inferred — the
            # record that makes a silent solver mis-ranking visible
            trace.record_solver_decision({
                "estimator": type(self).__name__,
                "n": n, "d": d, "k": k,
                "sparsity": sparsity,
                "num_machines": machines,
                "costs": {
                    type(solver).__name__: cost
                    for (cost, i), (solver, _) in zip(costs, options)
                },
                "chosen": type(choice.node).__name__,
                "weights": {
                    "cpu_weight": self.cpu_weight,
                    "mem_weight": self.mem_weight,
                    "network_weight": self.network_weight,
                    "lat_weight": self.lat_weight,
                },
                "provenance": dict(self._weight_provenance),
                "shape_source": shape_source,
                **({"streaming_restricted": True} if streaming else {}),
            })
        return choice
