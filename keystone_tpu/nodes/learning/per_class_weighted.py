"""Per-class weighted least squares (reference
``nodes/learning/PerClassWeightedLeastSquares.scala`` +
``internal/ReWeightedLeastSquares.scala``).

For every class c a separate weighted ridge problem is solved by block
coordinate descent:

    W_c = (X_zm^T diag(B_c) X_zm + lambda I) \\ X_zm^T (B_c .* y_c)

where B_c gives every example (1-w)/n baseline weight plus w/n_c for the
example's own class, X is centered by the class's joint feature mean
(w * class_mean + (1-w) * pop_mean), and y_c is the label column centered
by the joint label mean. The per-class solves are independent; each runs
as one jitted BCD program with all-reduced weighted Grams.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ensure_array, ArrayDataset, Dataset
from ...workflow.label_estimator import LabelEstimator
from .linear import BlockLinearMapper


class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        X = np.asarray(ds.numpy(), np.float32)
        L = np.asarray(labels.numpy(), np.float32)
        return self.fit_arrays(X, L)

    def fit_arrays(self, X: np.ndarray, L: np.ndarray) -> BlockLinearMapper:
        n, d = X.shape
        n_classes = L.shape[1]
        w = self.mixture_weight
        bs = self.block_size
        bounds = tuple((i, min(d, i + bs)) for i in range(0, d, bs))

        class_idx = np.argmax(L, axis=1)
        counts = np.bincount(class_idx, minlength=n_classes).astype(np.float64)
        counts = np.maximum(counts, 1.0)
        pop_mean = X.mean(axis=0)
        # per-class means and joint feature means (reference :127-169)
        onehot = np.zeros((n, n_classes), np.float32)
        onehot[np.arange(n), class_idx] = 1.0
        class_means = (onehot.T @ X) / counts[:, None].astype(np.float32)
        jfm = w * class_means + (1 - w) * pop_mean  # (C, d)
        joint_label_mean = (counts / n) * 2.0 * (1 - w) - 1.0 + 2.0 * w

        Xj = jnp.asarray(X)
        models = np.zeros((d, n_classes), np.float32)
        for c in range(n_classes):
            b_c = np.full(n, (1 - w) / n, np.float32)
            b_c[class_idx == c] += w / counts[c]
            y_c = (L[:, c] - joint_label_mean[c]).astype(np.float32)
            W_c = _solve_single_class(
                Xj,
                jnp.asarray(b_c),
                jnp.asarray(y_c),
                jnp.asarray(jfm[c]),
                jnp.float32(self.lam),
                bounds,
                self.num_iter,
            )
            models[:, c] = np.asarray(W_c)

        blocks = [models[lo:hi] for lo, hi in bounds]
        final_b = joint_label_mean - np.sum(jfm.T * models, axis=0)
        return BlockLinearMapper(blocks, bs, intercept=final_b.astype(np.float32))


@functools.partial(jax.jit, static_argnames=("bounds", "num_iter"))
def _solve_single_class(X, b, y, mu, lam, bounds, num_iter):
    """BCD for one class (reference ReWeightedLeastSquares.scala:37-135)."""
    by = b * y
    Ws = [jnp.zeros((hi - lo,), X.dtype) for lo, hi in bounds]
    factors = []
    for lo, hi in bounds:
        Xzm = X[:, lo:hi] - mu[lo:hi]
        aTa = Xzm.T @ (Xzm * b[:, None])
        A = aTa + lam * jnp.eye(hi - lo, dtype=X.dtype)
        factors.append(jax.scipy.linalg.cho_factor(A, lower=True))
    # residual r accumulates B .* (X_zm @ W)
    r = jnp.zeros_like(y)
    for _ in range(num_iter):
        for i, (lo, hi) in enumerate(bounds):
            Xzm = X[:, lo:hi] - mu[lo:hi]
            xw_old = Xzm @ Ws[i]
            r_minus = r - b * xw_old
            aTb = Xzm.T @ (by - r_minus)
            W_new = jax.scipy.linalg.cho_solve(factors[i], aTb)
            r = r + b * (Xzm @ (W_new - Ws[i]))
            Ws[i] = W_new
    return jnp.concatenate(Ws)
