"""Per-class weighted least squares (reference
``nodes/learning/PerClassWeightedLeastSquares.scala`` +
``internal/ReWeightedLeastSquares.scala``).

For every class c a separate weighted ridge problem is solved by block
coordinate descent:

    W_c = (X_zm^T diag(B_c) X_zm + lambda I) \\ X_zm^T (B_c .* y_c)

where B_c gives every example (1-w)/n baseline weight plus w/n_c for the
example's own class, X is centered by the class's joint feature mean
(w * class_mean + (1-w) * pop_mean), and y_c is the label column centered
by the joint label mean. The per-class solves are independent; each runs
as one jitted BCD program with all-reduced weighted Grams.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import (
    argmax_labels,
    ensure_array,
    fetch_to_host,
    ArrayDataset,
    Dataset,
)
from ...workflow.label_estimator import LabelEstimator
from .linear import BlockLinearMapper


class PerClassWeightedLeastSquaresEstimator(LabelEstimator):
    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        return self._fit_sharded(ds, labels)

    def fit_arrays(self, X: np.ndarray, L: np.ndarray) -> BlockLinearMapper:
        return self._fit_sharded(
            ArrayDataset.from_numpy(np.asarray(X, np.float32)),
            ArrayDataset.from_numpy(np.asarray(L, np.float32)),
        )

    def _fit_sharded(
        self, ds: ArrayDataset, labels: ArrayDataset
    ) -> BlockLinearMapper:
        """Per-class solves over the row-sharded feature matrix: every Gram
        and cross-product inside ``_solve_single_class`` contracts the
        sharded example axis, so XLA emits per-shard partials + psum (the
        reference's per-partition accumulate + treeReduce). X never leaves
        the mesh; only O(n) int32 class ids reach the host."""
        n, d = ds.n, ds.data.shape[1]
        n_classes = labels.data.shape[1]
        w = self.mixture_weight
        bs = self.block_size
        bounds = tuple((i, min(d, i + bs)) for i in range(0, d, bs))

        X, L = ds.data, labels.data
        mask = ds.mask.astype(jnp.float32)  # (padded_n,)
        cls_dev = argmax_labels(L)  # computed once, reused per class
        class_idx = fetch_to_host(cls_dev)[: n]
        counts = np.maximum(
            np.bincount(class_idx, minlength=n_classes).astype(np.float64), 1.0
        )
        # population / per-class means via sharded reductions
        pop_sum, class_sums = _label_stats(X, cls_dev, mask, n_classes)
        pop_mean = np.asarray(pop_sum) / n
        class_means = np.asarray(class_sums) / counts[:, None].astype(
            np.float32
        )
        jfm = (w * class_means + (1 - w) * pop_mean).astype(np.float32)
        joint_label_mean = (counts / n) * 2.0 * (1 - w) - 1.0 + 2.0 * w

        # ALL per-class solves in one dispatch: a Python loop would pay
        # two host round-trips per class (1000+ for ImageNet); lax.map
        # keeps the per-class working set while the whole sweep
        # compiles once. Solutions stay on device.
        models = _solve_all_classes(
            X,
            cls_dev,
            mask,
            L,
            jnp.asarray(jfm),
            jnp.asarray(joint_label_mean.astype(np.float32)),
            jnp.asarray(counts.astype(np.float32)),
            jnp.float32(self.lam),
            jnp.float32(n),
            jnp.float32(w),
            bounds,
            self.num_iter,
            n_classes,
        )  # (d, n_classes)

        blocks = [models[lo:hi] for lo, hi in bounds]
        final_b = (
            jnp.asarray(joint_label_mean)
            - jnp.sum(jnp.asarray(jfm).T * models, axis=0)
        )
        # pass the assembled matrix through so the mapper does not
        # re-concatenate the block views into a second (d, C) copy
        return BlockLinearMapper(
            blocks, bs, intercept=final_b.astype(jnp.float32),
            weights=models)


@functools.partial(jax.jit, static_argnames=("k",))
def _label_stats(X, cls, mask, k):
    """Masked population sum and per-class sums (onehot^T X), sharded."""
    Xm = X * mask[:, None]
    onehot = jax.nn.one_hot(cls, k, dtype=X.dtype) * mask[:, None]
    return jnp.einsum("nd->d", Xm), onehot.T @ Xm


@jax.jit
def _class_indicator(cls, c, mask):
    return (cls == c).astype(jnp.float32) * mask


@functools.partial(
    jax.jit, static_argnames=("bounds", "num_iter", "k"))
def _solve_all_classes(X, cls, mask, L, jfm, joint_label_mean, counts,
                       lam, n, w, bounds, num_iter, k):
    """Sweep every class's independent reweighted solve under one
    ``lax.map``: per-class weights/labels are built on the fly from the
    class-id vector, so the program is one dispatch regardless of k."""

    def body(c):
        onehot_c = _class_indicator(cls, c, mask)
        b_c = mask * ((1.0 - w) / n) + onehot_c * (w / counts[c])
        y_c = (jnp.take(L, c, axis=1) - joint_label_mean[c]) * mask
        return _solve_single_class(
            X, b_c, y_c, jfm[c], lam, bounds, num_iter)

    # solver-path GEMMs follow linalg's solver precision policy
    from ...ops.linalg import solver_precision

    with solver_precision():
        W_all, oks, ratios = jax.lax.map(body, jnp.arange(k))
    # conditioning ledger: every class's per-block breakdown predicate
    # and pivot ratio in ONE callback after the map (a per-iteration
    # callback inside the map body would serialize it — the bcd_scan
    # rule), so a class whose blocks took the eigh fallback is visible
    from ...observability.numerics import record_block_health

    record_block_health("per_class_bcd", oks.reshape(-1),
                        ratios.reshape(-1))
    return W_all.T  # (d, k)


@functools.partial(jax.jit, static_argnames=("bounds", "num_iter"))
def _solve_single_class(X, b, y, mu, lam, bounds, num_iter):
    """BCD for one class (reference ReWeightedLeastSquares.scala:37-135).

    Returns ``(W, oks, ratios)``: the stacked per-block breakdown
    predicates and pivot ratios ride out of the ``lax.map`` so the
    caller records them into the conditioning ledger in one callback."""
    from ...ops.linalg import _chol_health, _finite_or_eigh_solve

    by = b * y
    Ws = [jnp.zeros((hi - lo,), X.dtype) for lo, hi in bounds]
    factors = []
    factor_ok = []
    factor_ratio = []
    reg_fns = []  # rebuild A only inside a (rare) fallback branch

    def _make_reg(lo, hi):
        def reg():
            Xzm = X[:, lo:hi] - mu[lo:hi]
            return (Xzm.T @ (Xzm * b[:, None])
                    + lam * jnp.eye(hi - lo, dtype=X.dtype))
        return reg

    for lo, hi in bounds:
        reg_fn = _make_reg(lo, hi)
        G = reg_fn()
        L = jax.scipy.linalg.cho_factor(G, lower=True)
        factors.append(L)
        # shared collapsed-pivot gate: finite-but-garbage factors from
        # near-exact rank deficiency also take the eigh fallback
        ok, ratio = _chol_health(L[0], G)
        factor_ok.append(ok)
        factor_ratio.append(ratio)
        reg_fns.append(reg_fn)
    # residual r accumulates B .* (X_zm @ W)
    r = jnp.zeros_like(y)
    for _ in range(num_iter):
        for i, (lo, hi) in enumerate(bounds):
            Xzm = X[:, lo:hi] - mu[lo:hi]
            xw_old = Xzm @ Ws[i]
            r_minus = r - b * xw_old
            aTb = Xzm.T @ (by - r_minus)
            W_new = jax.scipy.linalg.cho_solve(factors[i], aTb)
            # f32 breakdown recovery (ops/linalg shared clamp policy)
            W_new = _finite_or_eigh_solve(
                W_new, reg_fns[i], aTb, ok=factor_ok[i])
            r = r + b * (Xzm @ (W_new - Ws[i]))
            Ws[i] = W_new
    return (jnp.concatenate(Ws), jnp.stack(factor_ok),
            jnp.stack(factor_ratio))
