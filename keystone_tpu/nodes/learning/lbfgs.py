"""LBFGS-based least-squares solvers (reference
``nodes/learning/LBFGS.scala`` + ``Gradient.scala``).

Objective (reference CostFun, LBFGS.scala:79-121):
    loss(W) = ||A W - B||^2 / (2 n) + (lambda/2) ||W||^2
with the gradient accumulated across the row-sharded data by XLA
all-reduce (the treeReduce replacement) inside one jitted L-BFGS program.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import linalg
from ...ops.lbfgs import lbfgs
from ...parallel.dataset import ensure_array, ArrayDataset, Dataset
from ...workflow.label_estimator import LabelEstimator
from ..stats import StandardScalerModel
from .linear import LinearMapper


class DenseLBFGSwithL2(LabelEstimator):
    """Dense least-squares via L-BFGS (reference LBFGS.scala:127-193).
    fit_intercept mean-centers features/labels and stores the scalers on
    the returned LinearMapper, exactly like the reference."""

    def __init__(
        self,
        fit_intercept: bool = True,
        num_corrections: int = 10,
        convergence_tol: float = 1e-4,
        num_iterations: int = 100,
        lam: float = 0.0,
    ):
        self.fit_intercept = fit_intercept
        self.num_corrections = num_corrections
        self.convergence_tol = convergence_tol
        self.num_iterations = num_iterations
        self.lam = lam

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset) -> LinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        n = ds.n
        X, Y = ds.data, labels.data
        mask = ds.mask

        if self.fit_intercept:
            x_mean = np.asarray(linalg.distributed_mean(X, n))
            y_mean = np.asarray(linalg.distributed_mean(Y, n))
        else:
            x_mean = np.zeros(X.shape[1], np.float32)
            y_mean = np.zeros(Y.shape[1], np.float32)

        W = _run_lbfgs(
            X,
            Y,
            jnp.asarray(x_mean),
            jnp.asarray(y_mean),
            mask,
            n,
            jnp.asarray(self.lam, X.dtype),
            self.num_iterations,
            self.num_corrections,
            self.convergence_tol,
        )
        if self.fit_intercept:
            return LinearMapper(
                np.asarray(W),
                intercept=y_mean,
                feature_scaler=StandardScalerModel(x_mean),
            )
        return LinearMapper(np.asarray(W))

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (LBFGS.scala:175-191) plus the TPU
        dispatch-latency term: L-BFGS is inherently iterative, one
        serial device round per iteration (measured ~375 ms fixed cost
        for 20 iterations at tiny compute on the axon chip, r5
        calibration). ``lat_w=0`` reproduces the reference surface."""
        flops = n * d * k / num_machines
        bytes_scanned = n * d / num_machines
        network = 2.0 * d * k * np.log2(max(num_machines, 1))
        return self.num_iterations * (
            max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
            + lat_w
        )


@functools.partial(
    jax.jit, static_argnames=("n", "num_iterations", "num_corrections", "tol")
)
def _run_lbfgs(X, Y, x_mean, y_mean, mask, n, lam, num_iterations,
               num_corrections, tol):
    m = mask[:, None].astype(X.dtype)
    Xc = (X - x_mean) * m
    Yc = (Y - y_mean) * m
    d, k = X.shape[1], Y.shape[1]

    def value_and_grad(W):
        R = Xc @ W - Yc  # padded rows contribute 0
        loss = 0.5 * jnp.sum(R * R) / n + 0.5 * lam * jnp.sum(W * W)
        grad = linalg.cross(Xc, R) / n + lam * W
        return loss, grad

    res = lbfgs(
        value_and_grad,
        jnp.zeros((d, k), X.dtype),
        max_iters=num_iterations,
        num_corrections=num_corrections,
        tol=tol,
    )
    return res.x


class SparseLBFGSwithL2(LabelEstimator):
    """Sparse-input least-squares via L-BFGS (reference
    ``LBFGS.scala:209-262`` + ``Gradient.scala:58-119``).

    TPU-native layout: the sparse batch becomes fixed-width padded COO
    arrays (indices/values), sharded over the mesh data axis like any
    ArrayDataset. The gradient A^T(AW - B) is a gather (W rows by index,
    weighted by values) plus a scatter-add — static shapes, one jitted
    L-BFGS program. ``fit_intercept`` uses the reference's ones-column
    trick (one extra COO slot per row).
    """

    def __init__(
        self,
        fit_intercept: bool = True,
        num_corrections: int = 10,
        convergence_tol: float = 1e-4,
        num_iterations: int = 100,
        lam: float = 0.0,
        sparse_overhead: float = 8.0,
    ):
        self.fit_intercept = fit_intercept
        self.num_corrections = num_corrections
        self.convergence_tol = convergence_tol
        self.num_iterations = num_iterations
        self.lam = lam
        self.sparse_overhead = sparse_overhead

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset):
        from .classifiers import SparseLinearMapper
        from ..util.sparse import pack_sparse_fit_inputs

        if isinstance(ds, ArrayDataset):
            raise TypeError(
                "SparseLBFGSwithL2 expects a host dataset of SparseVectors; "
                "dense arrays should use DenseLBFGSwithL2")
        indices, values, d, y_arr = pack_sparse_fit_inputs(ds, labels)
        n = len(y_arr)
        if self.fit_intercept:
            # ones column: index d, value 1 in an extra slot per row
            indices = np.concatenate(
                [indices, np.full((n, 1), d, np.int32)], axis=1)
            values = np.concatenate(
                [values, np.ones((n, 1), np.float32)], axis=1)
            d_aug = d + 1
        else:
            d_aug = d

        coo = ArrayDataset.from_numpy(
            {"indices": indices, "values": values})
        Y = ArrayDataset.from_numpy(np.asarray(y_arr, np.float32)).data

        W = _run_sparse_lbfgs(
            coo.data["indices"], coo.data["values"], Y, coo.mask,
            d_aug, n,
            jnp.asarray(self.lam, jnp.float32),
            self.num_iterations, self.num_corrections, self.convergence_tol,
            penalize_last=not self.fit_intercept,
        )
        W = np.asarray(W)
        if self.fit_intercept:
            return SparseLinearMapper(W[:-1], intercept=W[-1])
        return SparseLinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (LBFGS.scala:264-280) plus the TPU
        dispatch-latency term (one serial round per iteration; see
        ``DenseLBFGSwithL2.cost``)."""
        flops = n * sparsity * d * k / num_machines
        bytes_scanned = n * d * sparsity / num_machines
        network = 2.0 * d * k * np.log2(max(num_machines, 1))
        return self.num_iterations * (
            self.sparse_overhead * max(cpu_w * flops, mem_w * bytes_scanned)
            + net_w * network
            + lat_w
        )


@functools.partial(
    jax.jit,
    static_argnames=("d", "n", "num_iterations", "num_corrections", "tol",
                     "penalize_last"),
)
def _run_sparse_lbfgs(indices, values, Y, mask, d, n, lam, num_iterations,
                      num_corrections, tol, penalize_last=True):
    m = mask.astype(values.dtype)
    vals = values * m[:, None]  # padded rows contribute nothing
    Ym = Y * m[:, None]
    k = Y.shape[1]
    flat_idx = indices.reshape(-1)
    # with an intercept ones-column, the bias row is not regularized
    # (matches DenseLBFGSwithL2, whose intercept is the label mean)
    pen = jnp.ones((d, 1), jnp.float32)
    if not penalize_last:
        pen = pen.at[-1, 0].set(0.0)

    def value_and_grad(W):
        # A W: gather rows of W at the nz indices, weight, reduce over slots
        gathered = W[indices]                 # (rows, slots, k)
        pred = jnp.einsum("rs,rsk->rk", vals, gathered)
        R = pred - Ym
        Wp = W * pen
        loss = 0.5 * jnp.sum(R * R) / n + 0.5 * lam * jnp.sum(Wp * Wp)
        # A^T R: scatter-add value-weighted residual rows
        contrib = (vals[:, :, None] * R[:, None, :]).reshape(-1, k)
        grad = jnp.zeros_like(W).at[flat_idx].add(contrib) / n + lam * Wp
        return loss, grad

    res = lbfgs(
        value_and_grad,
        jnp.zeros((d, k), jnp.float32),
        max_iters=num_iterations,
        num_corrections=num_corrections,
        tol=tol,
    )
    return res.x
