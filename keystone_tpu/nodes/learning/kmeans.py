"""KMeans++ (reference ``nodes/learning/KMeansPlusPlus.scala``).

The fit is "driver-local" in the reference (collected matrix, Breeze);
here it is a replicated jitted Lloyd's loop with the same vectorized
GEMM distance trick. The distributed apply (per-partition batched GEMM,
reference :62-69) is the vmapped assignment over the sharded batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import Transformer


class KMeansModel(Transformer):
    """Nearest-center one-hot assignment (reference KMeansPlusPlus.scala:16-70)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, dtype=np.float32)  # (k, d)

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)

    # fitted-param protocol (PERFORMANCE.md rule 6): refitting the
    # centers never recompiles the assignment program
    def apply_params(self):
        params = self.__dict__.get("_jit_kmeans_params")
        if params is None:
            params = (jnp.asarray(self.means),)
            self.__dict__["_jit_kmeans_params"] = params
        return params

    def apply_with_params(self, params, x):
        (means,) = params
        sq_dist = (
            0.5 * jnp.sum(x * x)
            - x @ means.T
            + 0.5 * jnp.sum(means * means, axis=1)
        )
        k = means.shape[0]
        return (jnp.arange(k) == jnp.argmin(sq_dist)).astype(jnp.float32)

    def struct_key(self):
        return (KMeansModel, "assign")


class KMeansPlusPlusEstimator(Estimator):
    """k-means++ initialization + Lloyd's iterations
    (reference KMeansPlusPlus.scala:82-181). One round == pure k-means++
    init. Deterministic under ``seed``."""

    def __init__(self, num_means: int, max_iterations: int,
                 stop_tolerance: float = 1e-3, seed: int = 0):
        self.num_means = num_means
        self.max_iterations = max_iterations
        self.stop_tolerance = stop_tolerance
        self.seed = seed

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import map_last_dim

        return map_last_dim(self.num_means)

    def _fit(self, ds: Dataset) -> KMeansModel:
        X = ds.numpy() if isinstance(ds, ArrayDataset) else np.stack(ds.collect())
        return self.fit_matrix(np.asarray(X, np.float32))

    def fit_matrix(self, X: np.ndarray) -> KMeansModel:
        n, d = X.shape
        k = self.num_means
        rng = np.random.RandomState(self.seed)
        x_sq_half = 0.5 * np.sum(X * X, axis=1)

        # k-means++ seeding (reference :100-123)
        centers = np.zeros(k, dtype=np.int64)
        centers[0] = rng.randint(n)
        cur_sq_dist = None
        for i in range(k - 1):
            c = X[centers[i]]
            sq_to_new = x_sq_half - X @ c + 0.5 * np.dot(c, c)
            cur_sq_dist = (
                sq_to_new if cur_sq_dist is None else np.minimum(sq_to_new, cur_sq_dist)
            )
            probs = np.maximum(cur_sq_dist, 0.0)
            total = probs.sum()
            if total <= 0:
                centers[i + 1] = rng.randint(n)
            else:
                centers[i + 1] = rng.choice(n, p=probs / total)

        means = X[centers].copy()

        # Lloyd's iterations with cost-improvement stopping (reference
        # :125-178); means stay device-resident, only the cost scalar
        # crosses to host per iteration
        X_dev = jnp.asarray(X)
        means_dev = jnp.asarray(means)
        prev_cost = None
        for it in range(self.max_iterations):
            new_means, cost = _lloyd_step(X_dev, means_dev)
            cost = float(cost)
            if prev_cost is not None:
                improving = (prev_cost - cost) >= self.stop_tolerance * abs(prev_cost)
                if not improving:
                    break
            means_dev = new_means
            prev_cost = cost
        return KMeansModel(np.asarray(means_dev))


@jax.jit
def _lloyd_step(X, means):
    sq_dist = (
        0.5 * jnp.sum(X * X, axis=1, keepdims=True)
        - X @ means.T
        + 0.5 * jnp.sum(means * means, axis=1)
    )
    cost = jnp.mean(jnp.min(sq_dist, axis=1))
    assign = jax.nn.one_hot(jnp.argmin(sq_dist, axis=1), means.shape[0], dtype=X.dtype)
    mass = jnp.sum(assign, axis=0)
    # an emptied cluster keeps its previous center instead of going NaN
    # (0/0) and poisoning every later iteration
    new_means = jnp.where(
        (mass > 0)[:, None],
        (assign.T @ X) / jnp.maximum(mass, 1e-12)[:, None],
        means,
    )
    return new_means, cost
