"""Probabilistic classifiers and discriminant analysis.

TPU-native re-designs of reference ``nodes/learning/NaiveBayesModel.scala``,
``LogisticRegressionModel.scala``, ``LinearDiscriminantAnalysis.scala``,
and ``LocalLeastSquaresEstimator.scala``. Where the reference wraps Spark
MLlib trainers, the same models are trained directly: multinomial naive
Bayes from all-reduced per-class sums, multinomial logistic regression via
the in-tree jitted L-BFGS.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import linalg
from ...ops.lbfgs import lbfgs
from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.label_estimator import LabelEstimator
from ...workflow.transformer import Transformer
from ..stats import StandardScalerModel
from .linear import LinearMapper


class NaiveBayesModel(Transformer):
    """log-posterior scores pi + theta @ x
    (reference NaiveBayesModel.scala:49-53).

    Sparse-native like MLlib's model: a ``HostDataset`` of
    ``SparseVector`` items scores through the same padded-COO device
    einsum as :class:`SparseLinearMapper` (scores = x @ theta.T + pi) —
    never a densified (n, d) matrix (at 100k text features that dense
    copy is the whole cost)."""

    def __init__(self, pi: np.ndarray, theta: np.ndarray):
        self.pi = np.asarray(pi, dtype=np.float32)  # (k,)
        self.theta = np.asarray(theta, dtype=np.float32)  # (k, d)

    def apply(self, x):
        from ..util.sparse import SparseVector

        if isinstance(x, SparseVector):
            assert x.size == self.theta.shape[1], (
                f"sparse input size {x.size} != model dim "
                f"{self.theta.shape[1]}")
            return self.pi + self.theta[:, x.indices] @ x.values
        return self.pi + self.theta @ x

    # fitted-param protocol for the DENSE batch path (sparse inputs go
    # through the padded-COO apply_dataset override): a refitted model
    # never recompiles the scoring program (PERFORMANCE.md rule 6)
    def apply_params(self):
        params = self.__dict__.get("_jit_nb_params")
        if params is None:
            params = (jnp.asarray(self.pi), jnp.asarray(self.theta))
            self.__dict__["_jit_nb_params"] = params
        return params

    def apply_with_params(self, params, x):
        pi, theta = params
        return pi + theta @ x

    def struct_key(self):
        return (NaiveBayesModel, "score")

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from ..util.sparse import is_sparse_host

        if is_sparse_host(ds):
            return SparseLinearMapper(
                self.theta.T, intercept=self.pi).apply_dataset(ds)
        return super().apply_dataset(ds)


class NaiveBayesEstimator(LabelEstimator):
    """Multinomial naive Bayes with additive smoothing, the model MLlib's
    ``NaiveBayes.train`` produces (reference NaiveBayesModel.scala:56-68):
    pi_c = log((n_c + lam) / (n + k*lam)),
    theta_cj = log((sum_cj + lam) / (sum_c + d*lam)).
    Labels are int class ids. Dense ``ArrayDataset`` features sum per
    class on device; sparse ``HostDataset`` features (the text path,
    reference NewsgroupsPipeline.scala:24-31 feeds MLlib sparse vectors)
    accumulate on host without densifying."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = num_classes
        self.lam = lam

    def _fit(self, ds: Dataset, labels: Dataset) -> NaiveBayesModel:
        from ...parallel.dataset import HostDataset
        from ..util.sparse import SparseVector

        k = self.num_classes
        if isinstance(ds, HostDataset):
            items = ds.items
            if not (items and isinstance(items[0], SparseVector)):
                raise TypeError(
                    "NaiveBayesEstimator host path needs SparseVector items")
            if isinstance(labels, ArrayDataset):
                y = np.asarray(labels.numpy()).astype(np.int64).ravel()
            else:
                y = np.asarray(labels.collect(), np.int64).ravel()
            if len(items) != len(y):
                raise ValueError(
                    f"{len(items)} feature items vs {len(y)} labels")
            d = items[0].size
            sums = np.zeros((k, d), np.float64)
            for sv, c in zip(items, y):
                if sv.size != d:
                    raise ValueError(
                        f"item size {sv.size} != {d} (mixed feature spaces)")
                # SparseVector indices are coalesced-unique, so plain
                # fancy-index += is exact (and much faster than add.at)
                sums[c, sv.indices] += sv.values
            counts = np.bincount(y, minlength=k).astype(np.float64)
        else:
            assert isinstance(ds, ArrayDataset) and isinstance(
                labels, ArrayDataset)
            sums, counts = _per_class_sums(ds.data, labels.data, ds.mask, k)
            sums = np.asarray(sums, np.float64)
            counts = np.asarray(counts, np.float64)
        n = counts.sum()
        pi = np.log(counts + self.lam) - np.log(n + k * self.lam)
        theta = np.log(sums + self.lam) - np.log(
            sums.sum(axis=1, keepdims=True) + sums.shape[1] * self.lam
        )
        return NaiveBayesModel(pi, theta)


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _per_class_sums(X, y, mask, num_classes):
    onehot = jax.nn.one_hot(y, num_classes, dtype=X.dtype)
    onehot = onehot * mask[:, None].astype(X.dtype)
    sums = onehot.T @ X  # (k, d), all-reduced over the mesh
    counts = jnp.sum(onehot, axis=0)
    return sums, counts


class LogisticRegressionModel(Transformer):
    """argmax-class prediction from a multinomial logistic model
    (reference LogisticRegressionModel.scala: MLlib model.predict).
    Sparse inputs score via index gathers / the padded-COO device
    einsum, like the MLlib model over sparse vectors."""

    def __init__(self, weights: np.ndarray):
        self.weights = np.asarray(weights, dtype=np.float32)  # (d, k)

    def apply(self, x):
        from ..util.sparse import SparseVector

        if isinstance(x, SparseVector):
            assert x.size == self.weights.shape[0], (
                f"sparse input size {x.size} != model dim "
                f"{self.weights.shape[0]}")
            scores = x.values @ self.weights[x.indices]
            return jnp.argmax(scores, axis=-1).astype(jnp.int32)
        return jnp.argmax(x @ self.weights, axis=-1).astype(jnp.int32)

    # fitted-param protocol for the DENSE batch path (sparse inputs go
    # through the SparseLinearMapper apply_dataset override)
    def apply_params(self):
        params = self.__dict__.get("_jit_lr_params")
        if params is None:
            params = (jnp.asarray(self.weights),)
            self.__dict__["_jit_lr_params"] = params
        return params

    def apply_with_params(self, params, x):
        (W,) = params
        return jnp.argmax(x @ W, axis=-1).astype(jnp.int32)

    def struct_key(self):
        return (LogisticRegressionModel, "argmax")

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from ..util.sparse import is_sparse_host

        if is_sparse_host(ds):
            scores = SparseLinearMapper(self.weights).apply_dataset(ds)
            return scores.map_batch(
                lambda s: jnp.argmax(s, axis=-1).astype(jnp.int32))
        return super().apply_dataset(ds)


class LogisticRegressionEstimator(LabelEstimator):
    """Multinomial logistic regression trained by L-BFGS with L2
    (reference LogisticRegressionModel.scala:56-93, which defers to
    MLlib's LogisticRegressionWithLBFGS)."""

    def __init__(
        self,
        num_classes: int,
        reg_param: float = 0.0,
        num_iters: int = 100,
        convergence_tol: float = 1e-4,
    ):
        self.num_classes = num_classes
        self.reg_param = reg_param
        self.num_iters = num_iters
        self.convergence_tol = convergence_tol

    def _fit(self, ds: Dataset, labels: Dataset) -> LogisticRegressionModel:
        from ...parallel.dataset import HostDataset

        if isinstance(ds, HostDataset):
            return self._fit_sparse(ds, labels)
        assert isinstance(ds, ArrayDataset) and isinstance(labels, ArrayDataset)
        W = _fit_logistic(
            ds.data,
            labels.data,
            ds.mask,
            ds.n,
            self.num_classes,
            jnp.asarray(self.reg_param, ds.data.dtype),
            self.num_iters,
            self.convergence_tol,
        )
        return LogisticRegressionModel(np.asarray(W))

    def _fit_sparse(self, ds, labels) -> LogisticRegressionModel:
        """Padded-COO softmax L-BFGS — the sparse text path (reference
        AmazonReviewsPipeline.scala:25-33 fed MLlib sparse vectors; no
        (n, d) densification)."""
        from ..util.sparse import pack_sparse_fit_inputs

        indices, values, d, y = pack_sparse_fit_inputs(ds, labels)
        n = len(y)
        coo = ArrayDataset.from_numpy(
            {"indices": indices, "values": values})
        yd = ArrayDataset.from_numpy(y.astype(np.int32).ravel())
        W = _run_sparse_logistic(
            coo.data["indices"], coo.data["values"], yd.data, coo.mask,
            d, n, self.num_classes,
            jnp.asarray(self.reg_param, jnp.float32),
            self.num_iters, self.convergence_tol,
        )
        return LogisticRegressionModel(np.asarray(W))


@functools.partial(
    jax.jit, static_argnames=("num_classes", "num_iters", "tol", "n")
)
def _fit_logistic(X, y, mask, n, num_classes, lam, num_iters, tol):
    d = X.shape[1]
    onehot = jax.nn.one_hot(y, num_classes, dtype=X.dtype)
    m = mask.astype(X.dtype)

    def value_and_grad(W):
        logits = X @ W
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.sum(onehot * logp, axis=-1) * m
        loss = jnp.sum(ce) / n + 0.5 * lam * jnp.sum(W * W)
        p = jnp.exp(logp)
        grad = X.T @ ((p - onehot) * m[:, None]) / n + lam * W
        return loss, grad

    res = lbfgs(
        value_and_grad,
        jnp.zeros((d, num_classes), X.dtype),
        max_iters=num_iters,
        tol=tol,
    )
    return res.x


@functools.partial(
    jax.jit,
    static_argnames=("d", "n", "num_classes", "num_iters", "tol"))
def _run_sparse_logistic(indices, values, y, mask, d, n, num_classes,
                         lam, num_iters, tol):
    """Same objective as ``_fit_logistic``, with X as padded COO: logits
    by gather-einsum, gradient by scatter-add (the SparseLBFGSwithL2
    layout, ``lbfgs.py::_run_sparse_lbfgs``)."""
    m = mask.astype(values.dtype)
    vals = values * m[:, None]
    onehot = jax.nn.one_hot(y, num_classes, dtype=values.dtype)
    flat_idx = indices.reshape(-1)

    def value_and_grad(W):
        logits = jnp.einsum("rs,rsk->rk", vals, W[indices])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.sum(onehot * logp, axis=-1) * m
        loss = jnp.sum(ce) / n + 0.5 * lam * jnp.sum(W * W)
        G = (jnp.exp(logp) - onehot) * m[:, None]
        contrib = (vals[:, :, None] * G[:, None, :]).reshape(
            -1, num_classes)
        grad = jnp.zeros_like(W).at[flat_idx].add(contrib) / n + lam * W
        return loss, grad

    res = lbfgs(
        value_and_grad,
        jnp.zeros((d, num_classes), jnp.float32),
        max_iters=num_iters,
        tol=tol,
    )
    return res.x


class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA via eig(inv(Sw) Sb) on collected data
    (reference LinearDiscriminantAnalysis.scala:34-66)."""

    def __init__(self, num_dimensions: int):
        self.num_dimensions = num_dimensions

    def _fit(self, ds: Dataset, labels: Dataset) -> LinearMapper:
        X = np.asarray(ds.numpy(), np.float64)
        y = np.asarray(labels.numpy()).astype(np.int64).ravel()
        classes = np.unique(y)
        total_mean = X.mean(axis=0)
        d = X.shape[1]
        sw = np.zeros((d, d))
        sb = np.zeros((d, d))
        for c in classes:
            Xc = X[y == c]
            mu = Xc.mean(axis=0)
            dev = Xc - mu
            sw += dev.T @ dev
            md = (mu - total_mean)[None, :]
            sb += Xc.shape[0] * (md.T @ md)
        evals, evecs = np.linalg.eig(np.linalg.inv(sw) @ sb)
        order = np.argsort(-np.abs(evals))[: self.num_dimensions]
        W = np.real(evecs[:, order])
        return LinearMapper(W.astype(np.float32))


class LocalLeastSquaresEstimator(LabelEstimator):
    """Collect-to-host dual-form ridge for d >> n
    (reference LocalLeastSquaresEstimator.scala:26-60): center features and
    labels, solve W = A_zm^T ((A_zm A_zm^T + lam I) \\ b_zm)."""

    def __init__(self, lam: float):
        self.lam = lam

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset) -> LinearMapper:
        A = np.asarray(ds.numpy(), np.float32)
        b = np.asarray(labels.numpy(), np.float32)
        a_mean, b_mean = A.mean(axis=0), b.mean(axis=0)
        W = linalg.local_least_squares_dual(
            jnp.asarray(A - a_mean), jnp.asarray(b - b_mean), self.lam
        )
        return LinearMapper(
            np.asarray(W),
            intercept=b_mean,
            feature_scaler=StandardScalerModel(a_mean),
        )


@jax.jit
def _sparse_apply(indices, values, W, intercept):
    # jit lets XLA fuse the gather into the contraction instead of
    # materializing the (n, slots, k) gathered-weights tensor
    out = jnp.einsum("rs,rsk->rk", values, W[indices])
    return out if intercept is None else out + intercept


class SparseLinearMapper(Transformer):
    """Linear model over sparse inputs (reference
    ``SparseLinearMapper.scala:22-48``). Per-item apply takes a dense
    vector or a SparseVector (gather of the active weight rows); a batch
    of SparseVectors packs to padded COO and runs one device einsum."""

    def __init__(self, weights: np.ndarray, intercept: Optional[np.ndarray] = None):
        self.weights = np.asarray(weights, dtype=np.float32)
        self.intercept = None if intercept is None else np.asarray(intercept)

    def apply(self, x):
        from ..util.sparse import SparseVector

        if isinstance(x, SparseVector):
            assert x.size == self.weights.shape[0], (
                f"sparse input size {x.size} != model dim "
                f"{self.weights.shape[0]}")
            out = x.values @ self.weights[x.indices]
        else:
            out = x @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from ...parallel.dataset import HostDataset
        from ..util.sparse import SparseVector, sparse_batch

        if isinstance(ds, HostDataset) and ds.items and isinstance(
                ds.items[0], SparseVector):
            indices, values, size = sparse_batch(ds.items)
            assert size == self.weights.shape[0], (
                f"sparse input size {size} != model dim "
                f"{self.weights.shape[0]}")
            out = _sparse_apply(
                jnp.asarray(indices), jnp.asarray(values),
                jnp.asarray(self.weights),
                None if self.intercept is None
                else jnp.asarray(self.intercept))
            return ArrayDataset.from_numpy(np.asarray(out))
        return super().apply_dataset(ds)
