"""PCA family (reference ``nodes/learning/PCA.scala`` and
``DistributedPCA.scala``, ``ApproximatePCA.scala``).

The reference's driver-local LAPACK sgesvd becomes a replicated XLA SVD;
the distributed variant keeps the communication-avoiding TSQR structure
(per-shard QR + all-gather + QR) with only the small R factor crossing the
interconnect.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import linalg
from ...parallel.dataset import ArrayDataset, Dataset, HostDataset
from ...workflow.estimator import Estimator
from ...workflow.optimizable import NodeChoice, OptimizableEstimator
from ...workflow.transformer import Transformer


def enforce_matlab_sign_convention(pca: np.ndarray) -> np.ndarray:
    """Largest-magnitude element of each column becomes positive
    (reference PCA.scala:238-247)."""
    col_max = pca.max(axis=0)
    abs_max = np.abs(pca).max(axis=0)
    signs = np.where(col_max == abs_max, 1.0, -1.0).astype(pca.dtype)
    return pca * signs


class _PcaParamMixin:
    """Fitted-param protocol shared by the PCA projections: the fitted
    basis rides as a jit argument, so refits (new PCA on new data)
    never recompile the apply program (PERFORMANCE.md rule 6)."""

    def apply_params(self):
        params = self.__dict__.get("_jit_pca_params")
        if params is None:
            params = (jnp.asarray(self.pca_mat),)
            self.__dict__["_jit_pca_params"] = params  # _jit_*: unpickled
        return params

    def apply_with_params(self, params, x):
        (pca_mat,) = params
        return pca_mat.T @ x

    def struct_key(self):
        return (type(self), "project")


class PCATransformer(_PcaParamMixin, Transformer):
    """x -> pca_mat^T x (reference PCA.scala:19-30). pca_mat is (d, k)."""

    def __init__(self, pca_mat: np.ndarray):
        self.pca_mat = np.asarray(pca_mat, dtype=np.float32)

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)


class BatchPCATransformer(_PcaParamMixin, Transformer):
    """Per-item matrix projection: (d, cols) -> (k, cols)
    (reference PCA.scala:38-43)."""

    def __init__(self, pca_mat: np.ndarray):
        self.pca_mat = np.asarray(pca_mat, dtype=np.float32)

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)


@jax.jit
def _centered_svd_vt(X):
    # true-f32 (see _fit_zca): the "exact" local PCA must not sit
    # below the randomized one in fidelity
    with linalg.solver_precision():
        means = jnp.mean(X, axis=0)
        _, _, vt = jnp.linalg.svd(X - means, full_matrices=False)
        return vt


def _svd_pca(data: jnp.ndarray, dims: int) -> np.ndarray:
    vt = np.asarray(_centered_svd_vt(data))
    pca = enforce_matlab_sign_convention(vt.T)
    return pca[:, :dims]


class _PcaAbstractFitMixin:
    """abstract_fit shared by every PCA estimator: the fitted projection
    replaces the leading (descriptor) axis with ``dims``."""

    def abstract_fit(self, dep_specs):
        import jax

        from ...analysis.spec import Unknown

        dims = self.dims

        def apply_element(element):
            if isinstance(element, jax.ShapeDtypeStruct) and element.shape:
                return jax.ShapeDtypeStruct(
                    (dims,) + tuple(element.shape[1:]), element.dtype)
            return Unknown("pca input not an array element")

        return apply_element

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        """Fitted projection matrix: (d, dims) f32, d = the input
        element's leading (descriptor) axis."""
        import jax

        element = getattr(dep_specs[0], "element", None) if dep_specs \
            else None
        if not (isinstance(element, jax.ShapeDtypeStruct)
                and element.shape):
            return None
        return 4.0 * float(element.shape[0]) * self.dims


class PCAEstimator(_PcaAbstractFitMixin, Estimator):
    """Local PCA: collect the (sampled) data, center, SVD
    (reference PCA.scala:163-210)."""

    def __init__(self, dims: int):
        self.dims = dims

    def _fit(self, ds: Dataset) -> PCATransformer:
        X = _collect_matrix(ds)
        return PCATransformer(self.compute_pca(X))

    def compute_pca(self, X: np.ndarray) -> np.ndarray:
        return _svd_pca(jnp.asarray(X, jnp.float32), self.dims)

    #: gather + one big host SVD: two serial rounds.
    DISPATCH_ROUNDS = 2

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (PCA.scala:~213-226): all data moves to
        one machine. ``lat_w`` is the TPU dispatch-latency extension
        (see ``LinearMapEstimator.cost``); 0 reproduces the reference."""
        flops = n * d * d
        bytes_scanned = n * d
        network = n * d
        return (max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
                + lat_w * self.DISPATCH_ROUNDS)


@jax.jit
def _center_masked(X, means, mask):
    return (X - means) * mask[:, None].astype(X.dtype)


class DistributedPCAEstimator(_PcaAbstractFitMixin, Estimator):
    """Distributed PCA via TSQR: center by broadcast means, tree-QR to the
    small R factor, local SVD of R (reference DistributedPCA.scala:34-57)."""

    def __init__(self, dims: int):
        self.dims = dims

    def _fit(self, ds: Dataset) -> PCATransformer:
        assert isinstance(ds, ArrayDataset)
        n = ds.n
        X = ds.data
        means = linalg.distributed_mean(X, n)
        Xc = _center_masked(X, means, ds.mask)
        R = linalg.tsqr_r(Xc)
        _, _, vt = np.linalg.svd(np.asarray(R))
        pca = enforce_matlab_sign_convention(vt.T.astype(np.float32))
        return PCATransformer(pca[:, : self.dims])

    #: mean + center + device TSQR + small host SVD: four serial rounds.
    DISPATCH_ROUNDS = 4

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w,
             lat_w=0.0) -> float:
        """Reference cost model (DistributedPCA.scala:59-73) plus the
        TPU dispatch-latency term; ``lat_w=0`` reproduces the
        reference."""
        log2m = np.log2(max(num_machines, 1))
        flops = n * d * d / num_machines + d * d * d * log2m
        bytes_scanned = n * d
        network = d * d * log2m
        return (max(cpu_w * flops, mem_w * bytes_scanned) + net_w * network
                + lat_w * self.DISPATCH_ROUNDS)


@functools.partial(jax.jit, static_argnames=("q",))
def _randomized_svd_vt(X, omega, *, q: int):
    # true-f32 matmuls (see _fit_zca): power iterations at bf16
    # precision lose the small singular directions they exist to refine
    with linalg.solver_precision():
        means = jnp.mean(X, axis=0)
        A = X - means
        Y = A @ omega
        Q, _ = jnp.linalg.qr(Y)
        for _ in range(q):
            Q, _ = jnp.linalg.qr(A.T @ Q)
            Q, _ = jnp.linalg.qr(A @ Q)
        B = Q.T @ A
        _, _, vt = jnp.linalg.svd(B, full_matrices=False)
        return vt


class ApproximatePCAEstimator(_PcaAbstractFitMixin, Estimator):
    """Randomized-sketch PCA, Halko-Martinsson-Tropp algs 4.4/5.1
    (reference ApproximatePCA.scala:38-86): Gaussian sketch, q power
    iterations with intermediate QRs, then SVD of the projected matrix."""

    def __init__(self, dims: int, q: int = 10, p: int = 5, seed: int = 0):
        self.dims = dims
        self.q = q
        self.p = p
        self.seed = seed

    def _fit(self, ds: Dataset) -> PCATransformer:
        X = _collect_matrix(ds)
        return PCATransformer(self.approximate_pca(X))

    def approximate_pca(self, X: np.ndarray) -> np.ndarray:
        rng = np.random.RandomState(self.seed)
        ell = self.dims + self.p
        omega = rng.randn(X.shape[1], ell).astype(np.float32)
        vt = np.asarray(_randomized_svd_vt(
            jnp.asarray(X, jnp.float32), jnp.asarray(omega), q=self.q))
        pca = enforce_matlab_sign_convention(vt.T)
        return pca[:, : self.dims]


class LocalColumnPCAEstimator(_PcaAbstractFitMixin, Estimator):
    """Fits PCA treating each column of per-item matrices as a sample
    (reference PCA.scala:51-76); emits BatchPCATransformer."""

    def __init__(self, dims: int):
        self.dims = dims

    def _fit(self, ds: Dataset) -> BatchPCATransformer:
        cols = _stack_item_columns(ds)
        pca = PCAEstimator(self.dims).compute_pca(cols)
        return BatchPCATransformer(pca)


class DistributedColumnPCAEstimator(_PcaAbstractFitMixin, Estimator):
    """Distributed variant of the column PCA (reference PCA.scala:78-102)."""

    def __init__(self, dims: int):
        self.dims = dims

    def _fit(self, ds: Dataset) -> BatchPCATransformer:
        cols = _stack_item_columns(ds)
        fitted = DistributedPCAEstimator(self.dims).fit(
            ArrayDataset.from_numpy(cols)
        )
        return BatchPCATransformer(fitted.pca_mat)


class ColumnPCAEstimator(_PcaAbstractFitMixin, OptimizableEstimator):
    """Cost-model-optimizable column PCA (reference PCA.scala:118-156):
    the node-level optimizer picks local vs distributed by the reference's
    calibrated cost models; until then it runs distributed."""

    def __init__(self, dims: int, cpu_weight: float = None,
                 mem_weight: float = None, network_weight: float = None,
                 lat_weight: float = None):
        from .least_squares import (
            DEFAULT_CPU_WEIGHT, DEFAULT_LAT_WEIGHT, DEFAULT_MEM_WEIGHT,
            DEFAULT_NETWORK_WEIGHT)
        cpu_weight = DEFAULT_CPU_WEIGHT if cpu_weight is None else cpu_weight
        mem_weight = DEFAULT_MEM_WEIGHT if mem_weight is None else mem_weight
        network_weight = (DEFAULT_NETWORK_WEIGHT if network_weight is None
                          else network_weight)
        lat_weight = DEFAULT_LAT_WEIGHT if lat_weight is None else lat_weight
        self.dims = dims
        self.cpu_weight = cpu_weight
        self.mem_weight = mem_weight
        self.network_weight = network_weight
        self.lat_weight = lat_weight

    @property
    def options(self):
        return [LocalColumnPCAEstimator(self.dims),
                DistributedColumnPCAEstimator(self.dims)]

    @property
    def default(self):
        return DistributedColumnPCAEstimator(self.dims)

    def optimize(self, sample: Dataset, n: int, num_machines: int) -> NodeChoice:
        # the column PCA's sample unit is a (d, cols) matrix; the cost
        # models see total column count as n (reference PCA.scala:134-151)
        items = sample.collect()
        cols_per_item = int(np.asarray(items[0]).shape[-1]) if items else 1
        d = int(np.asarray(items[0]).shape[0]) if items else 1
        return self._choose(d, cols_per_item, n, num_machines)

    def optimize_static(self, spec, n: int, num_machines: int):
        """Static form: the (d, cols) item geometry comes from the
        analyzer's element spec instead of a sampled matrix."""
        element = getattr(spec, "element", None)
        if not (isinstance(element, jax.ShapeDtypeStruct)
                and len(element.shape) == 2):
            return None
        d, cols_per_item = (int(element.shape[0]), int(element.shape[1]))
        return self._choose(d, cols_per_item, n, num_machines)

    def _choose(self, d: int, cols_per_item: int, n: int,
                num_machines: int) -> NodeChoice:
        total_cols = n * cols_per_item
        local = PCAEstimator(self.dims)
        dist = DistributedPCAEstimator(self.dims)
        costs = [
            (local.cost(total_cols, d, self.dims, 1.0, num_machines,
                        self.cpu_weight, self.mem_weight,
                        self.network_weight, lat_w=self.lat_weight), 0),
            (dist.cost(total_cols, d, self.dims, 1.0, num_machines,
                       self.cpu_weight, self.mem_weight,
                       self.network_weight, lat_w=self.lat_weight), 1),
        ]
        _, best = min(costs)
        return NodeChoice(self.options[best])


def _collect_matrix(ds: Dataset) -> np.ndarray:
    if isinstance(ds, ArrayDataset):
        return ds.numpy()
    return np.stack(ds.collect())


def _stack_item_columns(ds: Dataset) -> np.ndarray:
    """Items are (d, cols) matrices; stack all columns as rows (the
    reference's matrixToColArray flatMap)."""
    if isinstance(ds, ArrayDataset):
        arr = ds.numpy()  # (n, d, cols)
        return arr.transpose(0, 2, 1).reshape(-1, arr.shape[1])
    items = ds.collect()
    return np.concatenate([np.asarray(m).T for m in items], axis=0)
