"""ZCA whitening (reference ``nodes/learning/ZCAWhitener.scala``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import Transformer


class ZCAWhitener(Transformer):
    """(x - means) @ whitener (reference ZCAWhitener.scala:12-18).
    Operates on patch matrices or vectors."""

    def __init__(self, whitener: np.ndarray, means: np.ndarray):
        self.whitener = np.asarray(whitener, dtype=np.float32)
        self.means = np.asarray(means, dtype=np.float32)

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)

    # fitted-param protocol (PERFORMANCE.md rule 6): refitting the
    # whitener never recompiles the apply program
    def apply_params(self):
        params = self.__dict__.get("_jit_zca_params")
        if params is None:
            params = (jnp.asarray(self.whitener), jnp.asarray(self.means))
            self.__dict__["_jit_zca_params"] = params
        return params

    def apply_with_params(self, params, x):
        W, means = params
        return (x - means) @ W

    def struct_key(self):
        return (ZCAWhitener, "whiten")


class ZCAWhitenerEstimator(Estimator):
    """Fit W = V diag((s^2/(n-1) + eps)^-1/2) V^T on the (sampled) input
    matrix (reference ZCAWhitenerEstimator.scala:30-76, which runs LAPACK
    sgesvd on the driver; here the SVD is a replicated XLA computation)."""

    def __init__(self, eps: float = 0.1):
        self.eps = eps

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import identity_fit

        return identity_fit(dep_specs)

    def fit_single(self, mat: np.ndarray) -> ZCAWhitener:
        W, means = _fit_zca(jnp.asarray(mat, jnp.float32), self.eps)
        return ZCAWhitener(np.asarray(W), np.asarray(means))

    def _fit(self, ds: Dataset) -> ZCAWhitener:
        assert isinstance(ds, ArrayDataset)
        return self.fit_single(ds.numpy())


@jax.jit
def _fit_zca(mat, eps):
    from ...ops.linalg import solver_precision

    # true-f32 matmuls: the reference ran this math in exact f32 on CPU
    # (PCA.scala uses Float); TPU default bf16 passes would be BELOW
    # reference precision for the whitener the north-star filters use
    with solver_precision():
        n = mat.shape[0]
        means = jnp.mean(mat, axis=0)
        centered = mat - means
        _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
        scale = (s * s / (n - 1.0) + eps) ** -0.5
        W = (vt.T * scale) @ vt
        return W, means
