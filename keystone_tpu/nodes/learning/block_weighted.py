"""Weighted block-coordinate least squares (reference
``nodes/learning/BlockWeightedLeastSquares.scala``).

Solves per-class mixture-weighted ridge: each class's solve interpolates
between its own class statistics (weight ``mixture_weight``) and the
population statistics (weight ``1 - mixture_weight``), per pass per
feature block (reference :102-320).

TPU-native structure — the mesh analogue of the reference's
``groupByClasses`` shuffle (:332-369, one class per Spark partition):

- The row-sharded feature matrix is regrouped ON DEVICE into a
  class-major tensor ``Xcm (C_pad, S, d)`` (class, within-class slot,
  feature) via one permutation gather; pad slots are zero. Classes shard
  over the ``model`` mesh axis, slots over ``data`` — so per-class work
  is class-parallel and within-class reductions are data-parallel.
- Per-class statistics (means, covariances, cross-products) are batched
  GEMMs contracting the slot axis: XLA turns the sharded contractions
  into per-class partial Grams + psum over ``data`` — the collective
  form of the reference's per-partition accumulate + treeReduce.
- Population statistics contract both (class, slot) axes → psum over
  the whole mesh.
- The per-class regularized solves are a batched Cholesky sharded over
  ``model``.

Only O(n) int32 label metadata (class ids) touches the host, to build
the permutation — the feature matrix itself never leaves the mesh
(asserted by ``tests/test_weighted_mesh.py`` under a transfer guard).
Padding inflates memory by max_class/mean_class like the reference's
one-class-per-partition stragglers.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.dataset import (
    argmax_labels,
    ensure_array,
    fetch_to_host,
    ArrayDataset,
    Dataset,
)
from ...parallel.mesh import DATA_AXIS, MODEL_AXIS, get_mesh
from ...workflow.label_estimator import LabelEstimator
from .linear import BlockLinearMapper


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
        solver: str = "auto",
        checkpoint_path: Optional[str] = None,
    ):
        if solver not in ("auto", "cholesky", "woodbury"):
            raise ValueError(f"unknown solver {solver!r}")
        if solver == "woodbury" and lam <= 0.0:
            # M = (1-w) pop_cov + lam I must be invertible; with lam=0 a
            # rank-deficient pop_cov would silently produce NaN weights
            raise ValueError("solver='woodbury' requires lam > 0")
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features
        self.solver = solver
        self.checkpoint_path = checkpoint_path

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1  # reference :44

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import labels_width_fit

        return labels_width_fit(dep_specs)

    # -- static HBM planning (analysis.resources) --------------------------
    def fitted_nbytes(self, dep_specs):
        from ...analysis.resources import linear_model_nbytes

        return linear_model_nbytes(dep_specs)

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        return self._fit_sharded(ds, labels)

    def fit_arrays(self, X: np.ndarray, L: np.ndarray) -> BlockLinearMapper:
        return self._fit_sharded(
            ArrayDataset.from_numpy(np.asarray(X, np.float32)),
            ArrayDataset.from_numpy(np.asarray(L, np.float32)),
        )

    def _fit_sharded(
        self, ds: ArrayDataset, labels: ArrayDataset
    ) -> BlockLinearMapper:
        n, d = ds.n, ds.data.shape[1]
        n_classes = labels.data.shape[1]
        w = self.mixture_weight
        lam = self.lam
        bs = self.block_size
        bounds = [(i, min(d, i + bs)) for i in range(0, d, bs)]
        mesh = ds.mesh or get_mesh()

        # --- label metadata (host, O(n) ints — the driver-side part) ---
        class_idx = fetch_to_host(argmax_labels(labels.data))[: n]
        counts = np.bincount(class_idx, minlength=n_classes).astype(np.int64)
        perm, C_pad, S = _class_major_perm(class_idx, counts, n_classes, mesh)

        # joint label mean (reference :148-156)
        joint_label_mean = (
            2.0 * w + 2.0 * (1 - w) * counts / n - 1.0
        ).astype(np.float32)

        # --- device: class-major layout, sharded (model, data, -) ---
        cm_sharding = NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS, None))
        perm_j = jax.device_put(
            jnp.asarray(perm), NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS))
        )
        Xcm = _to_class_major(ds.data, perm_j, out_sharding=cm_sharding)
        Lcm = _to_class_major(labels.data, perm_j, out_sharding=cm_sharding)
        mask_cm = (perm_j < np.int32(ds.data.shape[0])).astype(jnp.float32)
        # residual starts as centered labels, zeroed on pad slots
        Rcm = (Lcm - jnp.asarray(joint_label_mean)) * mask_cm[:, :, None]

        counts_f = jnp.asarray(
            np.concatenate(
                [counts, np.zeros(C_pad - n_classes, np.int64)]
            ).astype(np.float32)
        )

        models = [
            jnp.zeros((hi - lo, n_classes), jnp.float32) for lo, hi in bounds
        ]
        block_stats: List[Optional[tuple]] = [None] * len(bounds)
        block_chols: List[Optional[jax.Array]] = [None] * len(bounds)

        # per-pass checkpoint/resume (CLUSTER.md failure-recovery story)
        ckpt = None
        start_pass = 0
        if self.checkpoint_path:
            from ...utils.checkpoint import SolverCheckpoint

            ckpt = SolverCheckpoint(self.checkpoint_path)
            # untagged datasets get a cheap content fingerprint so a
            # stale checkpoint from *different* data of the same shape
            # can never warm-start this solve
            ds_id = ds.tag or _data_fingerprint(Xcm)
            labels_id = labels.tag or _data_fingerprint(Rcm)
            ckpt_key = (n, d, n_classes, bs, self.num_iter, float(lam),
                        float(w), self.solver, ds_id, labels_id)
            saved = ckpt.load(
                ckpt_key,
                model_shapes=[(hi - lo, n_classes) for lo, hi in bounds])
            if saved is not None and saved["pass"] + 1 < self.num_iter:
                models = [jnp.asarray(m) for m in saved["models"]]
                start_pass = saved["pass"] + 1
                # rebuild the residual from the restored model: the loop
                # invariant is Rcm = Rcm0 - sum_b Xb @ models[b] (masked)
                for b, (lo, hi) in enumerate(bounds):
                    Rcm = _update_residual_cm(
                        Rcm, Xcm[:, :, lo:hi], models[b], mask_cm)

        for pass_idx in range(start_pass, self.num_iter):
            for b, (lo, hi) in enumerate(bounds):
                # the whole block step is ONE dispatch; stats and the
                # population factor come back for reuse on later passes
                models[b], Rcm, block_stats[b], block_chols[b] = (
                    _block_pass_cm(
                        Xcm,
                        Rcm,
                        models[b],
                        mask_cm,
                        counts_f,
                        lo,
                        hi,
                        n,
                        w,
                        lam,
                        smodel=mesh.shape[MODEL_AXIS],
                        solver=self.solver,
                        stats=block_stats[b],
                        pop_factor=block_chols[b],
                    )
                )
            if ckpt is not None and pass_idx + 1 < self.num_iter:
                # a final-pass checkpoint has no consumer (resume needs
                # pass+1 < num_iter) — skip the write, and clear the
                # file once the solve completes
                ckpt.save(ckpt_key, pass_idx, models)
        if ckpt is not None:
            ckpt.clear()

        # everything stays on device: materializing (d, C) weights to
        # host here costs a multi-second d2h at ImageNet scale, and
        # apply() consumes them on device anyway
        W_blocks = models
        # intercept from per-block sums — no concatenated (d, C) copy
        # of the joint means or weights is ever materialized
        final_b = jnp.asarray(joint_label_mean) - sum(
            jnp.sum(s[2][:n_classes].T * m, axis=0)
            for s, m in zip(block_stats, W_blocks)
        )
        return BlockLinearMapper(
            W_blocks, bs, intercept=final_b.astype(jnp.float32)
        )




@jax.jit
def _fingerprint_moments(arr):
    # one fused pass; XLA reduces in-register, no full-size temporaries
    return (jnp.sum(arr), jnp.sum(jnp.square(arr)), jnp.sum(jnp.abs(arr)))


def _data_fingerprint(arr: jax.Array) -> str:
    """Cheap content identity for checkpoint keys: three global moments
    of the (sharded) array, fused into one jitted pass over data already
    resident in HBM. Two same-shape datasets colliding on all three to
    full f32 precision is vanishingly unlikely."""
    s, s2, sa = _fingerprint_moments(arr)
    return f"fp:{float(s):.8e}:{float(s2):.8e}:{float(sa):.8e}"


def _class_major_perm(class_idx, counts, n_classes, mesh):
    """Row permutation into the (C_pad, S) class-major layout.

    C_pad rounds the class count up to the ``model`` axis size, S rounds
    the largest class up to the ``data`` axis size; pad slots hold an
    out-of-bounds index so the gather fills zeros (mode='fill')."""
    smodel = max(mesh.shape[MODEL_AXIS], 1)
    sdata = max(mesh.shape[DATA_AXIS], 1)
    C_pad = -(-n_classes // smodel) * smodel
    max_count = max(int(counts.max()) if counts.size else 1, 1)
    S = -(-max_count // sdata) * sdata
    order = np.argsort(class_idx, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    oob = np.int32(2**31 - 1)
    perm = np.full((C_pad, S), oob, np.int32)
    for c in range(n_classes):
        cnt = int(counts[c])
        perm[c, :cnt] = order[starts[c] : starts[c] + cnt]
    return perm, C_pad, S


@functools.partial(jax.jit, static_argnames=("out_sharding",))
def _to_class_major(X, perm, out_sharding=None):
    out = jnp.take(X, perm, axis=0, mode="fill", fill_value=0)
    if out_sharding is not None:
        out = jax.lax.with_sharding_constraint(out, out_sharding)
    return out


@functools.partial(jax.jit, static_argnames=("n",))
def _block_stats_cm(Xb, mask, counts, n, w):
    """Population mean/cov + per-class joint means (reference :195-206),
    batched over the class axis. Xb (C_pad, S, d_b), mask (C_pad, S)."""
    Xm = Xb * mask[:, :, None]
    pop_mean = jnp.einsum("csd->d", Xm) / n
    pop_cov = jnp.einsum("csd,cse->de", Xm, Xm) / n - jnp.outer(
        pop_mean, pop_mean
    )
    cnt = jnp.maximum(counts, 1.0)[:, None]
    class_means = jnp.einsum("csd->cd", Xm) / cnt  # (C_pad, d_b)
    joint_means = w * class_means + (1 - w) * pop_mean
    return pop_mean, pop_cov, joint_means


#: Per-chunk budget for the batched (chunk, d_b, d_b) class covariance /
#: Cholesky tensors. The reference bounds this memory by processing one
#: class per partition; here the class axis is chunked so peak memory is
#: O(chunk * d_b^2) regardless of the class count (ImageNet: 1000 classes
#: at block_size 4096 would otherwise need ~67 GB per tensor).
_CLASS_CHUNK_BYTES = 1 << 30


def _class_chunk(C_pad: int, d_b: int, smodel: int, S: int = 0) -> int:
    if S:  # woodbury: per-class footprint is the rank-(S+2) factors,
        # not a (d_b, d_b) covariance — orders of magnitude smaller, so
        # chunks are correspondingly larger (fewer dispatches). ~6 such
        # tensors are live at peak (Xb, Xm, V, the cho_solve input copy
        # and result, MinvVT)
        per_class = 4 * (S + 2) * d_b * 6
    else:
        per_class = 4 * d_b * d_b
    chunk = max(int(_CLASS_CHUNK_BYTES // max(per_class, 1)), 1)
    if chunk >= C_pad:
        return C_pad
    # multiple of the model-axis size so each chunk shards evenly
    chunk = max((chunk // smodel) * smodel, smodel)
    return min(chunk, C_pad)


def _block_pass_cm(Xcm, Rcm, model_b, mask, counts, lo, hi, n, w, lam,
                   smodel=1, solver="auto", stats=None, pop_factor=None):
    """One coordinate-descent step for one block (reference :237-292):
    per-class joint statistics and solves, batched over classes and
    sharded (classes over 'model', slots over 'data'). The O(d_b^2)
    per-class tensors are built chunk-of-classes at a time, and the
    ENTIRE step — block slice, (first-pass) block statistics, pass
    globals, all chunk solves, residual update — is one jitted
    dispatch. The block start index is a dynamic operand, so every
    equal-width block shares one compiled trace.

    ``solver``: per-class system choice. "cholesky" is the direct
    batched factorization of each (d_b, d_b) joint covariance — O(C *
    d_b^3) of factorization work that maps poorly to the MXU.
    "woodbury" factors the class-INDEPENDENT part M = (1-w) pop_cov +
    lam I once and applies each class's statistics as a rank-(S+2)
    correction — O(d_b^3) once plus batched GEMMs and a small (S+2)^2
    solve per class, the MXU-friendly form. "auto" picks woodbury when
    the padded class size is well under the block width (the ImageNet FV
    regime: S ~ 1.3k slots vs d_b = 4096) and lam > 0 (M must be
    invertible).

    Returns ``(new_model_b, new_Rcm, stats, pop_factor)`` — the latter
    two for caller-side caching across passes (block statistics and the
    population factor are pass-invariant)."""
    C_pad, S, _ = Xcm.shape
    d_b = hi - lo
    if solver == "auto":
        solver = (
            "woodbury"
            if (S + 2) * 2 <= d_b and float(lam) > 0.0
            else "cholesky"
        )
    chunk = _class_chunk(
        C_pad, d_b, smodel, S=S if solver == "woodbury" else 0)

    # uniform chunks: one compiled shape serves every chunk (a ragged
    # tail chunk would cost a second XLA compile); the extra pad classes
    # are all-zero and their deltas fall outside delta[:k]
    nch = -(-C_pad // chunk)               # number of chunks
    chunk = -(-C_pad // nch)               # evenly spread classes
    chunk = -(-chunk // smodel) * smodel   # keep 'model'-shardable
    out = _block_pass_full(
        Xcm, Rcm, model_b, mask, counts, jnp.int32(lo),
        jnp.float32(w), jnp.float32(lam), stats, pop_factor,
        d_b=d_b, n=n, k=Rcm.shape[2], chunk=chunk, nch=nch,
        solver=solver, with_stats=stats is None)
    if stats is None:
        return out
    # cached passes return only the updated pair; threading the cached
    # stats through the jit would materialize fresh HBM copies of
    # pop_cov/joint_means/pop_factor every block step
    new_model, new_Rcm = out
    return new_model, new_Rcm, stats, pop_factor


@functools.partial(
    jax.jit, static_argnames=("d_b", "n", "k", "chunk", "nch", "solver",
                              "with_stats"))
def _block_pass_full(Xcm, Rcm, model_b, mask, counts, start, w, lam,
                     stats, pop_factor, *, d_b, n, k, chunk, nch,
                     solver, with_stats):
    """The whole block step in one program (see ``_block_pass_cm``).
    ``stats``/``pop_factor`` are ``None`` on a block's first pass
    (``with_stats=True``) and computed inside; later passes feed the
    cached values back in. ``pop_factor`` is the population Cholesky
    factor (woodbury) or the population covariance (cholesky)."""
    # solver-path GEMMs follow the solver precision policy (reference
    # solvers ran f64; bf16-pass Grams cost ~4e-2 relative solution
    # error at reference conditioning — see ops/linalg.SOLVER_PRECISION)
    from ...ops.linalg import solver_precision

    with solver_precision():
        Xb = jax.lax.dynamic_slice_in_dim(Xcm, start, d_b, axis=2)
        if with_stats:
            stats = _block_stats_cm(Xb, mask, counts, n, w)
            pop_cov = stats[1]
            pop_factor = (
                _pop_cholesky(pop_cov, w, lam) if solver == "woodbury"
                else pop_cov)
        pop_mean, _, joint_means = stats
        res, pop_xtr, residual_mean = _pass_globals(Xb, Rcm, mask, n, k)
        delta = _chunked_delta(
            Xb, res, mask, counts, joint_means, model_b, pop_xtr,
            residual_mean, pop_mean, pop_factor, w, lam,
            n=n, k=k, chunk=chunk, nch=nch, solver=solver)
        new_model = model_b + delta
        new_Rcm = _update_residual_cm(Rcm, Xb, delta, mask)
    if with_stats:
        return new_model, new_Rcm, stats, pop_factor
    return new_model, new_Rcm


def _chunked_delta(Xb, res, mask, counts, joint_means, model,
                   pop_xtr, residual_mean, pop_mean, pop_factor,
                   w, lam, *, n, k, chunk, nch, solver):
    """All per-class chunk solves of one block pass under ``lax.map``:
    the chunk-at-a-time HBM bound is kept while the whole pass belongs
    to the enclosing jit (a Python loop of per-chunk dispatches would
    pay a host round-trip per chunk)."""
    C_pad, S, d_b = Xb.shape
    total = nch * chunk
    if total != C_pad:
        cpad = total - C_pad
        Xb = jnp.pad(Xb, ((0, cpad), (0, 0), (0, 0)))
        res = jnp.pad(res, ((0, cpad), (0, 0)))
        mask = jnp.pad(mask, ((0, cpad), (0, 0)))
        counts = jnp.pad(counts, ((0, cpad),))
        joint_means = jnp.pad(joint_means, ((0, cpad), (0, 0)))

    c_ids = jnp.minimum(jnp.arange(total), k - 1)
    model_t = jnp.take(model, c_ids, axis=1).T            # (total, d_b)
    pop_xtr_t = jnp.take(pop_xtr, c_ids, axis=1).T        # (total, d_b)
    rmean_t = jnp.take(residual_mean, c_ids)              # (total,)

    def body(args):
        (Xc, resc, maskc, cntc, jmc, mc, pxc, rmc) = args
        if solver == "woodbury":
            return _chunk_solve_woodbury(
                Xc, resc, maskc, cntc, jmc, mc, pxc, rmc, pop_mean,
                pop_factor, n=n, w=w, lam=lam)
        return _chunk_solve(
            Xc, resc, maskc, cntc, jmc, mc, pxc, rmc, pop_mean,
            pop_factor, n=n, w=w, lam=lam)

    stacked = (
        Xb.reshape(nch, chunk, S, d_b),
        res.reshape(nch, chunk, S),
        mask.reshape(nch, chunk, S),
        counts.reshape(nch, chunk),
        joint_means.reshape(nch, chunk, d_b),
        model_t.reshape(nch, chunk, d_b),
        pop_xtr_t.reshape(nch, chunk, d_b),
        rmean_t.reshape(nch, chunk),
    )
    delta = jax.lax.map(body, stacked)                    # (nch, chunk, d_b)
    return delta.reshape(total, d_b)[:k].T                # (d_b, k)


@jax.jit
def _pop_cholesky(pop_cov, w, lam):
    d_b = pop_cov.shape[0]
    M = (1 - w) * pop_cov + lam * jnp.eye(d_b, dtype=pop_cov.dtype)
    L = jnp.linalg.cholesky(M)

    def repair(_):
        # f32 breakdown recovery (shared clamp policy:
        # ops/linalg.clamped_eigh — floor scaled so the reconstruction
        # is safely SPD): re-Cholesky the clamped matrix, with a
        # guaranteed-finite identity-scaled factor as the last resort
        # should even that factorization round indefinite.
        from ...ops.linalg import clamped_eigh

        V, wc = clamped_eigh(M)
        L2 = jnp.linalg.cholesky((V * wc) @ V.T)
        L3 = jnp.sqrt(jnp.max(wc)) * jnp.eye(d_b, dtype=M.dtype)
        return jax.lax.cond(
            jnp.all(jnp.isfinite(L2)), lambda _: L2, lambda _: L3, None)

    return jax.lax.cond(
        jnp.all(jnp.isfinite(L)), lambda _: L, repair, None)


@functools.partial(jax.jit, static_argnames=("n", "k"))
def _pass_globals(Xb, Rcm, mask, n, k):
    """Whole-population quantities for one pass: class-own residual
    columns, population cross-products, residual means."""
    C_pad = Xb.shape[0]
    Xm = Xb * mask[:, :, None]
    Rm = Rcm * mask[:, :, None]
    pop_xtr = jnp.einsum("csd,csk->dk", Xm, Rm) / n       # (d_b, k)
    residual_mean = jnp.einsum("csk->k", Rm) / n          # (k,)
    c_ids = jnp.minimum(jnp.arange(C_pad), k - 1)
    res = jnp.take_along_axis(Rm, c_ids[:, None, None], axis=2)[:, :, 0]
    return res, pop_xtr, residual_mean


def _chunk_stats(Xb, res, mask, counts, joint_means, model_c, pop_xtr_c,
                 residual_mean_c, pop_mean, w, lam):
    """Shared per-chunk statistics: class means, per-class cross-products,
    mean difference, and the regularized right-hand side."""
    Xm = Xb * mask[:, :, None]
    cnt = jnp.maximum(counts, 1.0)
    class_means = jnp.einsum("csd->cd", Xm) / cnt[:, None]
    class_xtr = jnp.einsum("csd,cs->cd", Xm, res) / cnt[:, None]
    mean_diff = class_means - pop_mean                    # (chunk, d_b)
    res_class_mean = jnp.einsum("cs->c", res) / cnt
    mean_mixture_wt = residual_mean_c * (1 - w) + w * res_class_mean
    joint_xtr = (
        (1 - w) * pop_xtr_c
        + w * class_xtr
        - joint_means * mean_mixture_wt[:, None]
    )
    rhs = joint_xtr - lam * model_c
    return Xm, cnt, class_means, mean_diff, rhs


@functools.partial(jax.jit, static_argnames=("n",))
def _chunk_solve(Xb, res, mask, counts, joint_means, model_c, pop_xtr_c,
                 residual_mean_c, pop_mean, pop_cov, n, w, lam):
    """Joint statistics + regularized solve for one chunk of classes
    (direct path): batched Cholesky of each (d_b, d_b) joint covariance."""
    d_b = Xb.shape[2]
    Xm, cnt, class_means, mean_diff, rhs = _chunk_stats(
        Xb, res, mask, counts, joint_means, model_c, pop_xtr_c,
        residual_mean_c, pop_mean, w, lam)
    class_cov = (
        jnp.einsum("csd,cse->cde", Xm, Xm) / cnt[:, None, None]
        - jnp.einsum("cd,ce->cde", class_means, class_means)
    )
    joint_xtx = (
        (1 - w) * pop_cov[None]
        + w * class_cov
        + (1 - w) * w * jnp.einsum("cd,ce->cde", mean_diff, mean_diff)
    )
    A = joint_xtx + lam * jnp.eye(d_b, dtype=Xb.dtype)[None]
    chol = jnp.linalg.cholesky(A)                         # SPD: batched Cholesky
    sol = jax.scipy.linalg.cho_solve((chol, True), rhs[..., None])[..., 0]

    def repair(_):
        # f32 breakdown recovery for the whole chunk (rare; shared
        # clamp policy: ops/linalg.clamped_eigh): batched clamped solve
        from ...ops.linalg import clamped_eigh

        V, wc = clamped_eigh(A)
        return jnp.einsum("cde,ce,cfe,cf->cd", V, 1.0 / wc, V, rhs)

    return jax.lax.cond(
        jnp.all(jnp.isfinite(sol)), lambda _: sol, repair, None)


@functools.partial(jax.jit, static_argnames=("n",))
def _chunk_solve_woodbury(Xb, res, mask, counts, joint_means, model_c,
                          pop_xtr_c, residual_mean_c, pop_mean, pop_chol,
                          n, w, lam):
    """Low-rank path: each class's system is

        A_c = M + V_c^T S V_c,   M = (1-w) pop_cov + lam I

    with V_c = [sqrt(w/n_c) X_c ; sqrt(w) mu_c ; sqrt((1-w)w) (mu_c-mu)]
    of rank S+2 and S = diag(+1...,-1,+1) (w class_cov = (w/n_c) X^T X
    - w mu mu^T contributes the one negative direction). Woodbury with
    the SHARED factor of M turns the per-class work into GEMMs plus one
    batched (S+2)x(S+2) general solve — no per-class d_b^3
    factorization. Identity holds for any invertible diag S:
    A^-1 = M^-1 - M^-1 V^T (S^-1 + V M^-1 V^T)^-1 V M^-1, S^-1 = S.
    Pad slots have zero rows in V, contributing identity rows in the
    inner system (harmless)."""
    chunk, S, d_b = Xb.shape
    Xm, cnt, class_means, mean_diff, rhs = _chunk_stats(
        Xb, res, mask, counts, joint_means, model_c, pop_xtr_c,
        residual_mean_c, pop_mean, w, lam)

    V = jnp.concatenate(
        [
            Xm * jnp.sqrt(w / cnt)[:, None, None],
            jnp.sqrt(w) * class_means[:, None, :],
            jnp.sqrt((1 - w) * w) * mean_diff[:, None, :],
        ],
        axis=1,
    )                                                     # (chunk, S+2, d_b)
    signs = jnp.concatenate(
        [jnp.ones(S, Xb.dtype), -jnp.ones(1, Xb.dtype),
         jnp.ones(1, Xb.dtype)]
    )

    def solve_M(B):  # B: (d_b, m) -> M^{-1} B via the shared factor
        return jax.scipy.linalg.cho_solve((pop_chol, True), B)

    Minv_rhs = solve_M(rhs.T).T                           # (chunk, d_b)
    MinvVT = (
        solve_M(V.reshape(-1, d_b).T).T.reshape(chunk, S + 2, d_b)
    )                                                     # rows: M^{-1} v_i
    K = jnp.einsum("cid,cjd->cij", V, MinvVT) + jnp.diag(signs)[None]
    u = jnp.einsum("cid,cd->ci", V, Minv_rhs)
    y = jnp.linalg.solve(K, u[..., None])[..., 0]
    return Minv_rhs - jnp.einsum("cid,ci->cd", MinvVT, y)


@jax.jit
def _update_residual_cm(Rcm, Xb, delta, mask):
    upd = jnp.einsum("csd,dk->csk", Xb, delta)
    return Rcm - upd * mask[:, :, None]
