"""Weighted block-coordinate least squares (reference
``nodes/learning/BlockWeightedLeastSquares.scala``).

Solves per-class mixture-weighted ridge: each class's solve interpolates
between its own class statistics (weight ``mixture_weight``) and the
population statistics (weight ``1 - mixture_weight``), per pass per
feature block (reference :102-320).

TPU-native structure: the reference re-shuffles to one-class-per-partition
(``groupByClasses``, :332-369) and runs per-partition local solves. Here
the data is sorted by class once (a host argsort + device gather — the
shuffle analogue), population Grams/cross-products are sharded GEMMs with
all-reduce, and the per-class statistics + solves run as a ``lax.scan``
over class segments of the sorted arrays (each step: masked dynamic slice,
class Gram on the MXU, replicated Cholesky solve).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ensure_array, ArrayDataset, Dataset
from ...workflow.label_estimator import LabelEstimator
from .linear import BlockLinearMapper


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        num_features: Optional[int] = None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1  # reference :44

    def _fit(self, ds: Dataset, labels: Dataset) -> BlockLinearMapper:
        ds, labels = ensure_array(ds), ensure_array(labels)
        X = np.asarray(ds.numpy(), np.float32)
        L = np.asarray(labels.numpy(), np.float32)
        return self.fit_arrays(X, L)

    def fit_arrays(self, X: np.ndarray, L: np.ndarray) -> BlockLinearMapper:
        n, d = X.shape
        n_classes = L.shape[1]
        w = self.mixture_weight
        lam = self.lam
        bs = self.block_size
        bounds = [(i, min(d, i + bs)) for i in range(0, d, bs)]

        # group by class: sort rows by class index (the reshuffle analogue)
        class_idx = np.argmax(L, axis=1)
        order = np.argsort(class_idx, kind="stable")
        Xs = X[order]
        Ls = L[order]
        sorted_idx = class_idx[order]
        counts = np.bincount(sorted_idx, minlength=n_classes).astype(np.int32)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
        max_seg = int(counts.max())

        # joint label mean (reference :148-156)
        joint_label_mean = 2 * w + 2 * (1 - w) * counts / n - 1.0

        # pad so per-class dynamic slices never run off the end
        Xs_pad = np.concatenate([Xs, np.zeros((max_seg, d), np.float32)])
        R = (Ls - joint_label_mean).astype(np.float32)
        R_pad = np.concatenate([R, np.zeros((max_seg, n_classes), np.float32)])

        Xs_j = jnp.asarray(Xs_pad)
        R_j = jnp.asarray(R_pad)
        starts_j = jnp.asarray(starts)
        counts_j = jnp.asarray(counts.astype(np.float32))

        models = [
            jnp.zeros((hi - lo, n_classes), jnp.float32) for lo, hi in bounds
        ]
        block_stats: List[Optional[tuple]] = [None] * len(bounds)

        for pass_idx in range(self.num_iter):
            for b, (lo, hi) in enumerate(bounds):
                Xb = Xs_j[:, lo:hi]
                if pass_idx == 0:
                    pop_mean, pop_cov, joint_means = _block_stats(
                        Xb, starts_j, counts_j, max_seg, n, w
                    )
                    block_stats[b] = (pop_mean, pop_cov, joint_means)
                else:
                    pop_mean, pop_cov, joint_means = block_stats[b]

                delta = _block_pass(
                    Xb,
                    R_j,
                    models[b],
                    pop_mean,
                    pop_cov,
                    joint_means,
                    starts_j,
                    counts_j,
                    max_seg,
                    n,
                    jnp.float32(w),
                    jnp.float32(lam),
                )
                models[b] = models[b] + delta
                R_j = _update_residual(R_j, Xb, delta, n)

        W_blocks = [np.asarray(m) for m in models]
        joint_means_all = np.concatenate(
            [np.asarray(s[2]) for s in block_stats], axis=1
        )  # (C, d)
        W_full = np.concatenate(W_blocks, axis=0)  # (d, C)
        final_b = joint_label_mean - np.sum(joint_means_all.T * W_full, axis=0)
        return BlockLinearMapper(
            W_blocks, bs, intercept=final_b.astype(np.float32)
        )


@functools.partial(jax.jit, static_argnames=("max_seg", "n"))
def _block_stats(Xb, starts, counts, max_seg, n, w):
    """Population mean/cov + per-class joint means (reference :195-206)."""
    Xreal = Xb[:n]
    pop_mean = jnp.sum(Xreal, axis=0) / n
    pop_cov = (Xreal.T @ Xreal) / n - jnp.outer(pop_mean, pop_mean)

    def class_mean(start, count):
        seg = jax.lax.dynamic_slice_in_dim(Xb, start, max_seg, axis=0)
        mask = (jnp.arange(max_seg) < count)[:, None].astype(Xb.dtype)
        return jnp.sum(seg * mask, axis=0) / jnp.maximum(count, 1.0)

    class_means = jax.vmap(class_mean)(starts, counts)  # (C, d_b)
    joint_means = w * class_means + (1 - w) * pop_mean
    return pop_mean, pop_cov, joint_means


@functools.partial(jax.jit, static_argnames=("max_seg", "n"))
def _block_pass(Xb, R, model, pop_mean, pop_cov, joint_means, starts, counts,
                max_seg, n, w, lam):
    """One coordinate-descent step for one block: per-class joint
    statistics and solves (reference :237-292)."""
    d_b = Xb.shape[1]
    Xreal, Rreal = Xb[:n], R[:n]
    pop_xtr = (Xreal.T @ Rreal) / n  # (d_b, C)
    residual_mean = jnp.sum(Rreal, axis=0) / n  # (C,)

    def per_class(c):
        start, count = starts[c], counts[c]
        seg = jax.lax.dynamic_slice_in_dim(Xb, start, max_seg, axis=0)
        res_seg = jax.lax.dynamic_slice_in_dim(R, start, max_seg, axis=0)[:, c]
        mask = (jnp.arange(max_seg) < count).astype(Xb.dtype)
        segm = seg * mask[:, None]
        cnt = jnp.maximum(count, 1.0)
        class_mean = jnp.sum(segm, axis=0) / cnt
        class_cov = (segm.T @ segm) / cnt - jnp.outer(class_mean, class_mean)
        res_m = res_seg * mask
        class_xtr = segm.T @ res_m / cnt
        mean_diff = class_mean - pop_mean

        joint_xtx = (
            pop_cov * (1 - w)
            + class_cov * w
            + jnp.outer(mean_diff, mean_diff) * (1 - w) * w
        )
        mean_mixture_wt = residual_mean[c] * (1 - w) + w * jnp.sum(res_m) / cnt
        joint_xtr = (
            pop_xtr[:, c] * (1 - w)
            + class_xtr * w
            - joint_means[c] * mean_mixture_wt
        )
        A = joint_xtx + lam * jnp.eye(d_b, dtype=Xb.dtype)
        rhs = joint_xtr - model[:, c] * lam
        return jnp.linalg.solve(A, rhs)

    delta = jax.lax.map(per_class, jnp.arange(joint_means.shape[0]))
    return delta.T  # (d_b, C)


@functools.partial(jax.jit, static_argnames=("n",))
def _update_residual(R, Xb, delta, n):
    upd = Xb[:n] @ delta
    return R.at[:n].add(-upd)
