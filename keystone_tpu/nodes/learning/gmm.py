"""Diagonal-covariance Gaussian mixtures (reference
``nodes/learning/GaussianMixtureModel.scala`` and
``GaussianMixtureModelEstimator.scala``), trained per Sanchez et al.'s
Fisher-vector guidelines.

The reference's driver-local EM becomes a jitted EM step; posterior
computation keeps the exact "Mahalanobis via GEMM" + max-shifted softmax +
aggressive thresholding structure that the Fisher-vector encoder depends
on.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import Transformer
from .kmeans import KMeansPlusPlusEstimator

KMEANS_PLUS_PLUS_INITIALIZATION = "kmeans++"
RANDOM_INITIALIZATION = "random"


def _posteriors(X, means, variances, weights, weight_threshold):
    """Thresholded posterior responsibilities of a batch (reference
    GaussianMixtureModel.scala:46-82). means/vars are (k, d), weights (k,)."""
    d = X.shape[-1]
    XSq = X * X
    sq_mahl = (
        XSq @ (0.5 / variances).T
        - X @ (means / variances).T
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    llh = (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
        - sq_mahl
    )
    shifted = llh - jnp.max(llh, axis=-1, keepdims=True)
    q = jnp.exp(shifted)
    q = q / jnp.sum(q, axis=-1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    return q / jnp.sum(q, axis=-1, keepdims=True)


class GaussianMixtureModel(Transformer):
    """Thresholded posterior assignment transformer. Stored column-major
    like the reference: means/variances are (d, k), weights (k,)."""

    def __init__(self, means, variances, weights, weight_threshold: float = 1e-4):
        self.means = np.asarray(means, dtype=np.float32)
        self.variances = np.asarray(variances, dtype=np.float32)
        self.weights = np.asarray(weights, dtype=np.float32)
        self.weight_threshold = weight_threshold
        assert self.means.shape == self.variances.shape
        assert self.weights.shape[0] == self.means.shape[1]

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def apply(self, x):
        return self.apply_with_params(self.apply_params(), x)

    # fitted-param protocol (PERFORMANCE.md rule 6): refitted mixtures
    # never recompile the posterior program
    def apply_params(self):
        params = self.__dict__.get("_jit_gmm_params")
        if params is None:
            params = (jnp.asarray(self.means.T),
                      jnp.asarray(self.variances.T),
                      jnp.asarray(self.weights))
            self.__dict__["_jit_gmm_params"] = params
        return params

    def apply_with_params(self, params, x):
        means_t, vars_t, weights = params
        return _posteriors(
            x[None, :], means_t, vars_t, weights, self.weight_threshold,
        )[0]

    def struct_key(self):
        return (GaussianMixtureModel, self.weight_threshold)

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str) -> "GaussianMixtureModel":
        """CSV artifact loading (reference GaussianMixtureModel.scala:97-105)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(means, variances, weights)

    def save(self, mean_file: str, vars_file: str, weights_file: str) -> None:
        """Write the CSV artifacts ``load`` reads (same layout the
        reference's MATLAB/enceval tooling produced: (d, k) means and
        variances, a k-vector of weights)."""
        np.savetxt(mean_file, self.means, delimiter=",")
        np.savetxt(vars_file, self.variances, delimiter=",")
        np.savetxt(weights_file, self.weights, delimiter=",")


class GaussianMixtureModelEstimator(Estimator):
    """EM for diagonal GMMs (reference GaussianMixtureModelEstimator.scala:
    25-190): kmeans++ (1 round) or range-uniform random init, variance
    floor max(small_var_thresh * global_var, abs_var_thresh), incremental
    LSE log-likelihood stopping, min-cluster-size abort."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        min_cluster_size: int = 40,
        stop_tolerance: float = 1e-4,
        weight_threshold: float = 1e-4,
        small_variance_threshold: float = 1e-2,
        absolute_variance_threshold: float = 1e-9,
        initialization_method: str = KMEANS_PLUS_PLUS_INITIALIZATION,
        seed: int = 0,
    ):
        assert min_cluster_size > 0 and max_iterations > 0
        self.k = k
        self.max_iterations = max_iterations
        self.min_cluster_size = min_cluster_size
        self.stop_tolerance = stop_tolerance
        self.weight_threshold = weight_threshold
        self.small_variance_threshold = small_variance_threshold
        self.absolute_variance_threshold = absolute_variance_threshold
        self.initialization_method = initialization_method
        self.seed = seed

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import map_last_dim

        return map_last_dim(self.k)

    def _fit(self, ds: Dataset) -> GaussianMixtureModel:
        X = ds.numpy() if isinstance(ds, ArrayDataset) else np.stack(ds.collect())
        return self.fit_matrix(np.asarray(X, np.float32))

    def fit_matrix(self, X: np.ndarray) -> GaussianMixtureModel:
        n, d = X.shape
        k = self.k
        # X crosses to device ONCE; XSq derives on device (a host XSq
        # would double the h2d volume over the dev tunnel)
        X_dev = jnp.asarray(np.asarray(X, np.float32))
        XSq_dev = X_dev * X_dev
        mean_global = X.mean(axis=0)
        var_global = (X * X).mean(axis=0) - mean_global**2

        if self.initialization_method == KMEANS_PLUS_PLUS_INITIALIZATION:
            km = KMeansPlusPlusEstimator(k, 1, seed=self.seed).fit_matrix(X)
            assign = jax.vmap(km.apply)(X_dev)  # (n, k), stays on device
            mass = jnp.maximum(jnp.sum(assign, axis=0), 1e-12)
            weights = mass / n
            means = (assign.T @ X_dev) / mass[:, None]
            variances = (assign.T @ XSq_dev) / mass[:, None] - means**2
        else:
            rng = np.random.RandomState(self.seed)
            col_min, col_max = X.min(axis=0), X.max(axis=0)
            col_range = col_max - col_min
            means = rng.rand(k, d).astype(np.float32) * col_range + col_min
            variances = np.full((k, d), 0.1, np.float32) * (col_range**2)
            weights = np.full(k, 1.0 / k, np.float32)

        var_lb_dev = jnp.asarray(
            np.maximum(
                self.small_variance_threshold * var_global,
                self.absolute_variance_threshold,
            ),
            jnp.float32,
        )

        # E and M both stay on device; only the 8-byte (cost, unbalanced)
        # pair crosses to host per iteration for the stopping decisions.
        # The old loop pulled the whole (n, k) responsibility matrix and
        # ran the M-step in numpy — minutes of d2h at FV-training scale.
        means = jnp.asarray(means, jnp.float32)
        variances = jnp.maximum(
            jnp.asarray(variances, jnp.float32), var_lb_dev)
        weights = jnp.asarray(weights, jnp.float32)

        prev_cost = None
        for it in range(self.max_iterations):
            new_means, new_vars, new_weights, llh_mean, unbalanced = _em_iter(
                X_dev, XSq_dev, means, variances, weights, var_lb_dev,
                self.weight_threshold, float(self.min_cluster_size),
            )
            cost = float(llh_mean)
            if prev_cost is not None:
                if (cost - prev_cost) < self.stop_tolerance * abs(prev_cost):
                    break
            if bool(unbalanced):
                # unbalanced clustering: stop updating (reference :176-178)
                break
            means, variances, weights = new_means, new_vars, new_weights
            prev_cost = cost

        return GaussianMixtureModel(
            np.asarray(means).T, np.asarray(variances).T,
            np.asarray(weights), self.weight_threshold
        )


@jax.jit
def _em_iter(X, XSq, means, variances, weights, var_lb,
             weight_threshold, min_cluster_size):
    """One full EM iteration on device. Returns the UPDATED parameters
    plus (mean log-likelihood of the CURRENT parameters, unbalanced
    flag); the host adopts the update only if neither stopping rule
    fires, preserving the reference's stop-without-updating semantics."""
    n = X.shape[0]
    q, llh_mean = _e_step(X, XSq, means, variances, weights,
                          weight_threshold)
    q_sum = jnp.sum(q, axis=0)
    unbalanced = jnp.any(q_sum < min_cluster_size)
    safe = jnp.maximum(q_sum, 1e-12)
    new_weights = q_sum / n
    # HIGHEST matmul precision: E[x^2] - mean^2 is cancellation-prone,
    # and the default bf16-pass matmul error would swamp small variances
    hi = jax.lax.Precision.HIGHEST
    new_means = jnp.matmul(q.T, X, precision=hi) / safe[:, None]
    new_vars = jnp.maximum(
        jnp.matmul(q.T, XSq, precision=hi) / safe[:, None]
        - new_means**2, var_lb)
    return new_means, new_vars, new_weights, llh_mean, unbalanced


@jax.jit
def _e_step(X, XSq, means, variances, weights, weight_threshold):
    d = X.shape[1]
    sq_mahl = (
        XSq @ (0.5 / variances).T
        - X @ (means / variances).T
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    llh = (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
        - sq_mahl
    )
    lse = jax.scipy.special.logsumexp(llh, axis=1)
    shifted = llh - jnp.max(llh, axis=1, keepdims=True)
    q = jnp.exp(shifted)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    return q, jnp.mean(lse)
