"""Diagonal-covariance Gaussian mixtures (reference
``nodes/learning/GaussianMixtureModel.scala`` and
``GaussianMixtureModelEstimator.scala``), trained per Sanchez et al.'s
Fisher-vector guidelines.

The reference's driver-local EM becomes a jitted EM step; posterior
computation keeps the exact "Mahalanobis via GEMM" + max-shifted softmax +
aggressive thresholding structure that the Fisher-vector encoder depends
on.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import Transformer
from .kmeans import KMeansPlusPlusEstimator

KMEANS_PLUS_PLUS_INITIALIZATION = "kmeans++"
RANDOM_INITIALIZATION = "random"


def _posteriors(X, means, variances, weights, weight_threshold):
    """Thresholded posterior responsibilities of a batch (reference
    GaussianMixtureModel.scala:46-82). means/vars are (k, d), weights (k,)."""
    d = X.shape[-1]
    XSq = X * X
    sq_mahl = (
        XSq @ (0.5 / variances).T
        - X @ (means / variances).T
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    llh = (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
        - sq_mahl
    )
    shifted = llh - jnp.max(llh, axis=-1, keepdims=True)
    q = jnp.exp(shifted)
    q = q / jnp.sum(q, axis=-1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    return q / jnp.sum(q, axis=-1, keepdims=True)


class GaussianMixtureModel(Transformer):
    """Thresholded posterior assignment transformer. Stored column-major
    like the reference: means/variances are (d, k), weights (k,)."""

    def __init__(self, means, variances, weights, weight_threshold: float = 1e-4):
        self.means = np.asarray(means, dtype=np.float32)
        self.variances = np.asarray(variances, dtype=np.float32)
        self.weights = np.asarray(weights, dtype=np.float32)
        self.weight_threshold = weight_threshold
        assert self.means.shape == self.variances.shape
        assert self.weights.shape[0] == self.means.shape[1]

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def apply(self, x):
        return _posteriors(
            x[None, :],
            jnp.asarray(self.means.T),
            jnp.asarray(self.variances.T),
            jnp.asarray(self.weights),
            self.weight_threshold,
        )[0]

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str) -> "GaussianMixtureModel":
        """CSV artifact loading (reference GaussianMixtureModel.scala:97-105)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(means, variances, weights)


class GaussianMixtureModelEstimator(Estimator):
    """EM for diagonal GMMs (reference GaussianMixtureModelEstimator.scala:
    25-190): kmeans++ (1 round) or range-uniform random init, variance
    floor max(small_var_thresh * global_var, abs_var_thresh), incremental
    LSE log-likelihood stopping, min-cluster-size abort."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        min_cluster_size: int = 40,
        stop_tolerance: float = 1e-4,
        weight_threshold: float = 1e-4,
        small_variance_threshold: float = 1e-2,
        absolute_variance_threshold: float = 1e-9,
        initialization_method: str = KMEANS_PLUS_PLUS_INITIALIZATION,
        seed: int = 0,
    ):
        assert min_cluster_size > 0 and max_iterations > 0
        self.k = k
        self.max_iterations = max_iterations
        self.min_cluster_size = min_cluster_size
        self.stop_tolerance = stop_tolerance
        self.weight_threshold = weight_threshold
        self.small_variance_threshold = small_variance_threshold
        self.absolute_variance_threshold = absolute_variance_threshold
        self.initialization_method = initialization_method
        self.seed = seed

    def _fit(self, ds: Dataset) -> GaussianMixtureModel:
        X = ds.numpy() if isinstance(ds, ArrayDataset) else np.stack(ds.collect())
        return self.fit_matrix(np.asarray(X, np.float32))

    def fit_matrix(self, X: np.ndarray) -> GaussianMixtureModel:
        n, d = X.shape
        k = self.k
        XSq = X * X
        mean_global = X.mean(axis=0)
        var_global = XSq.mean(axis=0) - mean_global**2

        if self.initialization_method == KMEANS_PLUS_PLUS_INITIALIZATION:
            km = KMeansPlusPlusEstimator(k, 1, seed=self.seed).fit_matrix(X)
            assign = np.asarray(
                jax.vmap(km.apply)(jnp.asarray(X))
            )
            mass = assign.sum(axis=0)
            mass = np.maximum(mass, 1e-12)
            weights = mass / n
            means = (assign.T @ X) / mass[:, None]
            variances = (assign.T @ XSq) / mass[:, None] - means**2
        else:
            rng = np.random.RandomState(self.seed)
            col_min, col_max = X.min(axis=0), X.max(axis=0)
            col_range = col_max - col_min
            means = rng.rand(k, d).astype(np.float32) * col_range + col_min
            variances = np.full((k, d), 0.1, np.float32) * (col_range**2)
            weights = np.full(k, 1.0 / k, np.float32)

        var_lb = np.maximum(
            self.small_variance_threshold * var_global,
            self.absolute_variance_threshold,
        )
        variances = np.maximum(variances, var_lb)

        prev_cost = None
        for it in range(self.max_iterations):
            q, llh_mean = _e_step(
                jnp.asarray(X),
                jnp.asarray(means, jnp.float32),
                jnp.asarray(variances, jnp.float32),
                jnp.asarray(weights, jnp.float32),
                self.weight_threshold,
            )
            cost = float(llh_mean)
            if prev_cost is not None:
                if (cost - prev_cost) < self.stop_tolerance * abs(prev_cost):
                    break
            q = np.asarray(q)
            q_sum = q.sum(axis=0)
            if (q_sum < self.min_cluster_size).any():
                # unbalanced clustering: stop updating (reference :176-178)
                break
            weights = q_sum / n
            means = (q.T @ X) / q_sum[:, None]
            variances = (q.T @ XSq) / q_sum[:, None] - means**2
            variances = np.maximum(variances, var_lb)
            prev_cost = cost

        return GaussianMixtureModel(
            means.T, variances.T, weights, self.weight_threshold
        )


@jax.jit
def _e_step(X, means, variances, weights, weight_threshold):
    d = X.shape[1]
    XSq = X * X
    sq_mahl = (
        XSq @ (0.5 / variances).T
        - X @ (means / variances).T
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    llh = (
        -0.5 * d * jnp.log(2 * jnp.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
        - sq_mahl
    )
    lse = jax.scipy.special.logsumexp(llh, axis=1)
    shifted = llh - jnp.max(llh, axis=1, keepdims=True)
    q = jnp.exp(shifted)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    return q, jnp.mean(lse)
