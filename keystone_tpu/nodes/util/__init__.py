"""Utility nodes (reference ``nodes/util``, SURVEY.md section 2.8)."""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.transformer import Transformer


class ClassLabelIndicatorsFromIntLabels(Transformer):
    """int label -> +-1 one-hot vector
    (reference ``util/ClassLabelIndicators.scala:15-34``)."""

    def __init__(self, num_classes: int):
        assert num_classes > 1, "numClasses must be > 1"
        self.num_classes = num_classes

    def apply(self, label):
        idx = jnp.arange(self.num_classes)
        return jnp.where(idx == label, 1.0, -1.0).astype(jnp.float32)


class ClassLabelIndicatorsFromIntArrayLabels(Transformer):
    """multi-label int array -> +-1 multi-hot vector
    (reference ``util/ClassLabelIndicators.scala:41-55``). Inputs are
    fixed-width padded label arrays with -1 for missing entries (the TPU
    layout for ragged label sets)."""

    def __init__(self, num_classes: int):
        assert num_classes > 1, "numClasses must be > 1"
        self.num_classes = num_classes

    def apply(self, labels):
        base = jnp.full((self.num_classes,), -1.0, dtype=jnp.float32)
        valid = labels >= 0
        onehot = jax.nn.one_hot(
            jnp.where(valid, labels, 0), self.num_classes, dtype=jnp.float32
        )
        hits = jnp.sum(onehot * valid[:, None].astype(jnp.float32), axis=0)
        return jnp.where(hits > 0, 1.0, base)


class VectorCombiner(Transformer):
    """Concatenate a gathered tuple of vectors into one vector
    (reference ``util/VectorCombiner.scala:12-14``)."""

    def apply(self, xs):
        return jnp.concatenate(list(xs), axis=-1)


class MaxClassifier(Transformer):
    """argmax (reference ``util/MaxClassifier.scala:9-11``)."""

    def apply(self, x):
        return jnp.argmax(x, axis=-1).astype(jnp.int32)


class TopKClassifier(Transformer):
    """Indices of the k largest values, descending
    (reference ``util/TopKClassifier.scala:9-11``)."""

    def __init__(self, k: int):
        self.k = k

    def apply(self, x):
        _, idx = jax.lax.top_k(x, self.k)
        return idx.astype(jnp.int32)


class VectorSplitter(Transformer):
    """Split the feature dimension into blocks of ``block_size``
    (reference ``util/VectorSplitter.scala:11-36``). Returns a tuple of
    sub-vectors per item; block boundaries are static."""

    def __init__(self, block_size: int, num_features: int = None):
        self.block_size = block_size
        self.num_features = num_features

    def _bounds(self, d: int):
        bs = self.block_size
        nb = (d + bs - 1) // bs
        return [(i * bs, min(d, (i + 1) * bs)) for i in range(nb)]

    def apply(self, x):
        d = self.num_features or x.shape[-1]
        return tuple(x[..., lo:hi] for lo, hi in self._bounds(d))


class FloatToDouble(Transformer):
    """Precision promotion (reference ``util/FloatToDouble.scala``). On TPU
    f64 is unsupported; this promotes to the highest available float so
    downstream solvers run at full precision."""

    def apply(self, x):
        return x.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)


class DoubleToFloat(Transformer):
    def apply(self, x):
        return x.astype(jnp.float32)


class MatrixVectorizer(Transformer):
    """Flatten a matrix into a vector, column-major to match Breeze's
    ``toDenseVector`` (reference ``util/MatrixVectorizer.scala``)."""

    def apply(self, x):
        return x.T.reshape(-1)


class Densify(Transformer):
    """Sparse -> dense passthrough (reference ``util/Densify.scala:10-21``).
    ArrayDatasets are already dense; sparse host datasets are stacked."""

    def apply(self, x):
        if hasattr(x, "todense"):
            return jnp.asarray(x.todense())
        return x

    def apply_dataset(self, ds: Dataset) -> Dataset:
        from ...parallel.dataset import HostDataset

        if isinstance(ds, ArrayDataset):
            return ds
        from ...parallel.dataset import is_streaming

        if is_streaming(ds):
            # StreamingDataset: chunks are already dense device arrays;
            # collect() here would silently materialize the stream
            return ds
        items = ds.collect()
        dense = [
            np.asarray(
                it.todense() if hasattr(it, "todense") else it, dtype=np.float32
            ).ravel()
            for it in items
        ]
        return ArrayDataset.from_items(dense)

    def abstract_single(self, elements):
        from ...analysis.spec import SparseSpec, Unknown

        (e,) = elements
        if isinstance(e, SparseSpec):
            if e.size is None:
                return Unknown("sparse element of unknown size")
            return jax.ShapeDtypeStruct((e.size,), np.float32)
        return super().abstract_single(elements)


class Cast(Transformer):
    def __init__(self, dtype: str):
        self.dtype = dtype

    def apply(self, x):
        return x.astype(self.dtype)


from .sparse import (  # noqa: E402
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseFeatureVectorizer,
    SparseVector,
    Sparsify,
    sparse_batch,
)


class LabelAugmenter(Transformer):
    """Repeat each item ``mult`` times, item-major — aligns labels/ids
    with patch-augmented data (reference
    ``RandomPatchCifarAugmented.LabelAugmenter``)."""

    def __init__(self, mult: int):
        self.mult = mult

    def apply(self, x):
        return x

    def abstract_eval(self, dep_specs):
        from ...analysis.spec import DatasetSpec

        out = super().abstract_eval(dep_specs)
        if isinstance(out, DatasetSpec) and out.n is not None:
            return DatasetSpec(out.element, n=out.n * self.mult,
                               host=out.host, sparsity=out.sparsity)
        return out

    def apply_dataset(self, ds: Dataset) -> Dataset:
        if isinstance(ds, ArrayDataset):
            arr = ds.numpy()
            rep = jax.tree_util.tree_map(
                lambda x: np.repeat(x, self.mult, axis=0), arr)
            return ArrayDataset.from_numpy(rep)
        from ...parallel.dataset import HostDataset

        return HostDataset(
            [it for it in ds.collect() for _ in range(self.mult)])
