"""Sparse feature vectors and sparse featurization nodes.

The reference represents sparse features as Breeze ``SparseVector``s built
by ``SparseFeatureVectorizer`` from (feature, value) pair lists, with the
feature space chosen by ``CommonSparseFeatures`` (top-K by frequency) or
``AllSparseFeatures`` (reference ``nodes/util/CommonSparseFeatures.scala``,
``AllSparseFeatures.scala``, ``SparseFeatureVectorizer.scala``).

TPU-native layout: a host :class:`SparseVector` (sorted int32 indices +
f32 values) per item, and :func:`sparse_batch` which packs a batch into
fixed-width padded COO device arrays — the static-shape form the sparse
solver kernels (gather/scatter on the MXU-adjacent VPU) consume.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset, HostDataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import HostTransformer, Transformer


class SparseVector:
    """Host sparse vector: sorted unique indices + values + logical size."""

    __slots__ = ("indices", "values", "size")

    def __init__(self, indices, values, size: int):
        idx = np.asarray(indices, dtype=np.int32)
        val = np.asarray(values, dtype=np.float32)
        # Coalesce duplicates by summing, so todense() and the padded-COO
        # einsum paths (which sum contributions) agree. np.unique also
        # sorts, which the class invariant requires.
        uniq, inverse = np.unique(idx, return_inverse=True)
        summed = np.zeros(uniq.shape[0], dtype=np.float32)
        np.add.at(summed, inverse, val)
        self.indices = uniq
        self.values = summed
        self.size = int(size)

    @staticmethod
    def from_dict(tf: Dict[int, float], size: int) -> "SparseVector":
        if not tf:
            return SparseVector(np.zeros(0, np.int32), np.zeros(0, np.float32), size)
        items = sorted(tf.items())
        idx, val = zip(*items)
        return SparseVector(np.asarray(idx), np.asarray(val), size)

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    def todense(self) -> np.ndarray:
        out = np.zeros(self.size, dtype=np.float32)
        out[self.indices] = self.values
        return out

    def __eq__(self, other):
        return (
            isinstance(other, SparseVector)
            and self.size == other.size
            and np.array_equal(self.indices, other.indices)
            and np.array_equal(self.values, other.values)
        )

    def __repr__(self):
        return f"SparseVector(nnz={self.nnz}, size={self.size})"


def sparse_batch(items: Sequence[SparseVector], max_nnz: Optional[int] = None,
                 allow_truncate: bool = False):
    """Pack SparseVectors into padded COO arrays.

    Returns ``(indices int32[n, m], values f32[n, m], size)`` where padding
    entries have index 0 and value 0 — linear ops (gathers weighted by
    value) are exact without a mask. A vector with more than ``max_nnz``
    entries is an error unless ``allow_truncate`` (lossy) is requested.
    """
    n = len(items)
    size = items[0].size if items else 0
    m = max_nnz or max((it.nnz for it in items), default=1)
    m = max(m, 1)
    indices = np.zeros((n, m), dtype=np.int32)
    values = np.zeros((n, m), dtype=np.float32)
    for i, it in enumerate(items):
        if it.nnz > m and not allow_truncate:
            raise ValueError(
                f"item {i} has nnz={it.nnz} > max_nnz={m}; pass "
                "allow_truncate=True to drop features")
        k = min(it.nnz, m)
        if it.size != size:
            raise ValueError(
                f"item {i} has size {it.size} != {size} (mixed feature "
                "spaces in one sparse batch)")
        indices[i, :k] = it.indices[:k]
        values[i, :k] = it.values[:k]
    return indices, values, size


def is_sparse_host(ds) -> bool:
    """True for a HostDataset whose items are SparseVectors — the shared
    dispatch predicate of every sparse-input model path."""
    return (isinstance(ds, HostDataset) and bool(ds.items)
            and isinstance(ds.items[0], SparseVector))


def pack_sparse_fit_inputs(ds, labels):
    """Collect a sparse host dataset + labels into aligned arrays for a
    solver: ``(indices, values, size, y ndarray)``. Validates item types,
    uniform feature-space size, and feature/label alignment — the shared
    preamble of SparseLBFGSwithL2 / sparse NaiveBayes / sparse logistic."""
    items = ds.collect()
    if not (items and isinstance(items[0], SparseVector)):
        raise TypeError("sparse fit needs a host dataset of SparseVectors")
    indices, values, size = sparse_batch(items)
    if isinstance(labels, ArrayDataset):
        y = np.asarray(labels.numpy())
    else:
        y = np.asarray(labels.collect())
    if len(items) != len(y):
        raise ValueError(
            f"labels ({len(y)} rows) do not align with data "
            f"({len(items)} rows)")
    return indices, values, size, y


class Sparsify(HostTransformer):
    """Dense vector -> SparseVector (reference ``util/Sparsify.scala``)."""

    def apply(self, x) -> SparseVector:
        if isinstance(x, SparseVector):
            return x
        x = np.asarray(x)
        idx = np.nonzero(x)[0]
        return SparseVector(idx, x[idx], x.shape[0])

    def abstract_single(self, elements):
        import jax

        from ...analysis.spec import SparseSpec

        (e,) = elements
        if isinstance(e, SparseSpec):
            return e
        if isinstance(e, jax.ShapeDtypeStruct) and len(e.shape) == 1:
            return SparseSpec(int(e.shape[0]))
        return super().abstract_single(elements)


class SparseFeatureVectorizer(HostTransformer):
    """(feature, value) pairs -> SparseVector over a fixed feature space
    (reference ``util/SparseFeatureVectorizer.scala:7-18``); features
    outside the space are dropped."""

    def __init__(self, feature_space: Dict[Any, int]):
        self.feature_space = dict(feature_space)

    def eq_key(self):
        return (SparseFeatureVectorizer, id(self.feature_space))

    def apply(self, pairs: Sequence[Tuple[Any, float]]) -> SparseVector:
        space = self.feature_space
        tf: Dict[int, float] = {}
        for feat, value in pairs:
            j = space.get(_key(feat))
            if j is not None:
                tf[j] = tf.get(j, 0.0) + float(value)
        return SparseVector.from_dict(tf, len(space))


def _key(feat: Any) -> Any:
    # normalize list-like ngram keys to hashable tuples
    if isinstance(feat, list):
        return tuple(feat)
    return feat


def _iter_pairs(ds: Dataset):
    for item in ds.collect():
        for feat, value in item:
            yield _key(feat), float(value)


class CommonSparseFeatures(Estimator):
    """Keep the ``num_features`` most frequent features, ordered by
    decreasing count then earliest appearance (reference
    ``CommonSparseFeatures.scala:20-64``: count + min unique id,
    per-partition takeOrdered + treeReduce merge — here one deterministic
    host pass)."""

    def __init__(self, num_features: int):
        self.num_features = int(num_features)

    def _fit(self, ds: Dataset) -> SparseFeatureVectorizer:
        counts: Dict[Any, int] = {}
        first: Dict[Any, int] = {}
        i = 0
        for feat, _ in _iter_pairs(ds):
            counts[feat] = counts.get(feat, 0) + 1
            if feat not in first:
                first[feat] = i
            i += 1
        top = sorted(counts, key=lambda f: (-counts[f], first[f]))
        top = top[: self.num_features]
        return SparseFeatureVectorizer({f: j for j, f in enumerate(top)})


class AllSparseFeatures(Estimator):
    """Keep every observed feature, ordered by earliest appearance
    (reference ``AllSparseFeatures.scala:15-27``)."""

    def _fit(self, ds: Dataset) -> SparseFeatureVectorizer:
        space: Dict[Any, int] = {}
        for feat, _ in _iter_pairs(ds):
            if feat not in space:
                space[feat] = len(space)
        return SparseFeatureVectorizer(space)
