"""Sampling nodes (reference ``stats/Sampling.scala``)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset, HostDataset
from ...workflow.transformer import Transformer


class Sampler(Transformer):
    """Random subsample of approximately ``size`` items (reference
    ``Sampler``: RDD takeSample without replacement). Deterministic seed."""

    def __init__(self, size: int, seed: int = 42):
        self.size = size
        self.seed = seed

    def apply(self, x):
        return x

    def apply_dataset(self, ds: Dataset) -> Dataset:
        n = len(ds)
        take = min(self.size, n)
        rng = np.random.RandomState(self.seed)
        idx = rng.choice(n, size=take, replace=False)
        idx.sort()
        if isinstance(ds, ArrayDataset):
            import jax

            # gather ON DEVICE: the input may be huge (e.g. every window
            # of every training image); pulling it to host to select a
            # small sample is a multi-GB transfer for a few-MB result
            idx_dev = jnp.asarray(idx)
            data = jax.tree_util.tree_map(
                lambda x: jnp.take(x, idx_dev, axis=0), ds.data
            )
            return ArrayDataset(data, take, ds.mesh)
        items = ds.collect()
        return HostDataset([items[i] for i in idx])

    def abstract_eval(self, dep_specs):
        from ...analysis.spec import DatasetSpec

        out = super().abstract_eval(dep_specs)
        if isinstance(out, DatasetSpec) and out.n is not None:
            return DatasetSpec(out.element, n=min(self.size, out.n),
                               host=out.host, sparsity=out.sparsity)
        return out


class ColumnSampler(Transformer):
    """Sample ``num_cols`` columns of each per-item (d, cols) matrix
    (reference ``ColumnSampler``, used to subsample SIFT descriptors)."""

    def __init__(self, num_cols: int, seed: int = 42):
        self.num_cols = num_cols
        self.seed = seed

    def apply(self, x):
        # deterministic per-node sample of columns; jax-traceable via fixed
        # host-side indices requires static col count, so sample uniformly
        # with a fixed numpy draw over the static shape
        cols = x.shape[-1]
        rng = np.random.RandomState(self.seed)
        idx = rng.choice(cols, size=min(self.num_cols, cols), replace=False)
        idx.sort()
        return x[..., jnp.asarray(idx)]


def sample_rows(mat: np.ndarray, num_rows: int, seed: int = 0) -> np.ndarray:
    """Random row subset (reference ``MatrixUtils.sampleRows``)."""
    rng = np.random.RandomState(seed)
    take = min(num_rows, mat.shape[0])
    idx = rng.choice(mat.shape[0], size=take, replace=False)
    idx.sort()
    return np.asarray(mat)[idx]
