"""Statistical feature nodes.

TPU-native re-designs of the reference's ``nodes/stats`` package
(SURVEY.md section 2.7). Every node's per-item ``apply`` is jax-traceable,
so batch execution is a single fused XLA program over the sharded batch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...parallel.dataset import ArrayDataset, Dataset
from ...workflow.estimator import Estimator
from ...workflow.transformer import Transformer

EPS = 2.2e-16  # matches the reference's varConstant floor usage


class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed +-1 vector
    (reference ``stats/RandomSignNode.scala:11-23``)."""

    def __init__(self, signs: np.ndarray):
        self.signs = np.asarray(signs, dtype=np.float32)

    @staticmethod
    def create(size: int, seed: int = 0) -> "RandomSignNode":
        rng = np.random.RandomState(seed)
        return RandomSignNode(2.0 * rng.randint(0, 2, size=size) - 1.0)

    def apply(self, x):
        return x * self.signs


class PaddedFFT(Transformer):
    """Zero-pad to the next power of two, FFT, keep the real part of the
    first half (reference ``stats/PaddedFFT.scala:13-20``)."""

    def apply(self, x):
        n = x.shape[-1]
        padded = 1 << (n - 1).bit_length()
        xp = jnp.concatenate(
            [x, jnp.zeros((padded - n,), x.dtype)], axis=-1
        )
        return jnp.real(jnp.fft.fft(xp))[: padded // 2].astype(x.dtype)


class LinearRectifier(Transformer):
    """f(x) = max(max_val, x - alpha)
    (reference ``stats/LinearRectifier.scala:12-17``)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = float(max_val)
        self.alpha = float(alpha)

    def apply(self, x):
        return jnp.maximum(self.max_val, x - self.alpha)


class NormalizeRows(Transformer):
    """L2-normalize each vector, flooring the norm at machine epsilon
    (reference ``stats/NormalizeRows.scala:8-14``)."""

    def apply(self, x):
        norm = jnp.maximum(jnp.linalg.norm(x), EPS)
        return x / norm


class SignedHellingerMapper(Transformer):
    """sign(x) * sqrt(|x|) (reference ``stats/SignedHellingerMapper.scala``)."""

    def apply(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class BatchSignedHellingerMapper(Transformer):
    """Matrix-input variant (applied to per-image descriptor matrices)."""

    def apply(self, x):
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class CosineRandomFeatures(Transformer):
    """Random Fourier features cos(x W^T + b)
    (reference ``stats/CosineRandomFeatures.scala:19-60``)."""

    def __init__(self, W: np.ndarray, b: np.ndarray):
        self.W = np.asarray(W, dtype=np.float32)  # (out, in)
        self.b = np.asarray(b, dtype=np.float32)  # (out,)
        assert self.b.shape[0] == self.W.shape[0]

    @staticmethod
    def create(
        num_input_features: int,
        num_output_features: int,
        gamma: float,
        w_dist: str = "gaussian",
        b_dist: str = "uniform",
        seed: int = 0,
    ) -> "CosineRandomFeatures":
        rng = np.random.RandomState(seed)
        if w_dist == "gaussian":
            W = rng.randn(num_output_features, num_input_features)
        elif w_dist == "cauchy":
            W = rng.standard_cauchy((num_output_features, num_input_features))
        elif w_dist == "uniform":
            W = rng.rand(num_output_features, num_input_features)
        else:
            raise ValueError(w_dist)
        W = W * gamma
        if b_dist == "uniform":
            b = rng.rand(num_output_features) * 2 * np.pi
        elif b_dist == "gaussian":
            b = rng.randn(num_output_features) * 2 * np.pi
        else:
            raise ValueError(b_dist)
        return CosineRandomFeatures(W, b)

    def apply(self, x):
        return jnp.cos(x @ self.W.T + self.b)


@jax.jit
def _center_scale_batch(X, mean, inv_std):
    """Whole-batch scaler apply with params as ARGUMENTS (not baked HLO
    constants): one compiled program serves every fitted scaler, so
    refitting on new data never recompiles (see
    ``nodes/learning/linear._affine_apply_batch`` for the rationale)."""
    return (X - mean) * inv_std


class StandardScalerModel(Transformer):
    """(x - mean) [/ std] (reference ``stats/StandardScaler.scala:16-31``)."""

    def __init__(self, mean: np.ndarray, std: Optional[np.ndarray] = None):
        self.mean = np.asarray(mean)
        self.std = None if std is None else np.asarray(std)

    def apply(self, x):
        out = x - self.mean
        if self.std is not None:
            out = out / self.std
        return out

    def apply_dataset(self, ds: Dataset) -> Dataset:
        if isinstance(ds, ArrayDataset):
            m, inv = self.apply_params()
            return ds.map_batch(lambda X: _center_scale_batch(X, m, inv))
        return super().apply_dataset(ds)

    # fitted-param protocol: fused chains thread these as jit arguments
    fusion_safe = True

    def apply_params(self):
        params = self.__dict__.get("_jit_scale_params")
        if params is None:
            mean = jnp.asarray(self.mean, jnp.float32)
            inv = (jnp.ones_like(mean) if self.std is None
                   else jnp.asarray(1.0 / self.std, jnp.float32))
            params = (mean, inv)
            self.__dict__["_jit_scale_params"] = params  # _jit_*: unpickled
        return params

    def apply_with_params(self, params, x):
        mean, inv = params
        return (x - mean) * inv

    def struct_key(self):
        return (StandardScalerModel, "center_scale")


class StandardScaler(Estimator):
    """Fit column means (and optionally stds) over the dataset.

    The reference aggregates a MultivariateOnlineSummarizer via
    treeAggregate (``stats/StandardScaler.scala:44-58``); here the moments
    are two all-reduced column sums over the sharded batch. Degenerate
    stds (NaN/inf/<eps) are replaced by 1.0, as in the reference.
    """

    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-12):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def abstract_fit(self, dep_specs):
        from ...analysis.spec import identity_fit

        return identity_fit(dep_specs)

    # -- static HBM planning (analysis.resources) --------------------------
    def carry_nbytes(self, dep_specs):
        from ...analysis.resources import moments_carry_nbytes

        return moments_carry_nbytes(dep_specs)

    def fitted_nbytes(self, dep_specs):
        from ...analysis.resources import moments_carry_nbytes

        # fitted model = mean + std, same footprint as the moment carry
        return moments_carry_nbytes(dep_specs)

    def _fit(self, ds: Dataset) -> StandardScalerModel:
        assert isinstance(ds, ArrayDataset), "StandardScaler needs array data"
        s, sq = _moments(ds.data)
        return self.finalize((s, sq, ds.n))

    # -- streaming fit (accumulate/finalize protocol) ----------------------
    def accumulate(self, carry, chunk):
        """Fold one chunk's column sums / sums-of-squares into the carry
        (padded rows are zero, so the moments stay exact); the resident
        ``_fit`` is the one-chunk special case of this."""
        assert isinstance(chunk, ArrayDataset), \
            "StandardScaler streams over array chunks"
        if carry is None:
            # replicated zero init + the SAME update program as every
            # later chunk: seeding from _moments(chunk.data) handed
            # chunk 2 a differently-SHARDED carry, so _accum_moments
            # compiled twice per fit (jax's cache keys on input
            # shardings) — flagged by the PR 9 fit fence, same fix as
            # the least-squares Gram carry
            from ...parallel.mesh import replicated_zeros

            d = chunk.data.shape[1]
            carry = tuple(replicated_zeros(
                chunk.mesh, ((d,), (d,)))) + (0,)
        S, SQ, n = carry
        S, SQ = _accum_moments(S, SQ, chunk.data)
        return (S, SQ, n + chunk.n)

    def finalize(self, carry) -> StandardScalerModel:
        s, sq, n = carry
        mean = np.asarray(s, dtype=np.float64) / n
        if not self.normalize_std_dev:
            return StandardScalerModel(mean.astype(np.float32))
        # unbiased sample variance, matching MultivariateOnlineSummarizer
        var = (np.asarray(sq, dtype=np.float64) - n * mean * mean) / max(n - 1, 1)
        std = np.sqrt(np.maximum(var, 0.0))
        bad = ~np.isfinite(std) | (np.abs(std) < self.eps)
        std = np.where(bad, 1.0, std)
        return StandardScalerModel(
            mean.astype(np.float32), std.astype(np.float32)
        )


@jax.jit
def _moments(X):
    # promote INTEGER chunks to f32 (a uint8-wire chunk fed straight to
    # the scaler must not wrap its X*X mod 256); float inputs keep
    # their width — f64 moments stay f64 under jax_enable_x64
    if not jnp.issubdtype(X.dtype, jnp.floating):
        X = X.astype(jnp.float32)
    return jnp.sum(X, axis=0), jnp.sum(X * X, axis=0)


def _accum_moments_impl(S, SQ, X):
    if not jnp.issubdtype(X.dtype, jnp.floating):
        X = X.astype(jnp.float32)
    return S + jnp.sum(X, axis=0), SQ + jnp.sum(X * X, axis=0)


from ...utils.donation import donating_jit  # noqa: E402


def _moments_probe(d: int = 8, n: int = 16):
    S, f32 = jax.ShapeDtypeStruct, np.float32
    return ((S((d,), f32), S((d,), f32), S((n, d), f32)), {})


#: the streamed moment carry donates (S, SQ): the per-chunk update
#: writes into the old moment buffers instead of reallocating them —
#: same in-place discipline as the least-squares Gram carry
#: (``nodes.learning.linear._gram_carry_update``). The probe keeps the
#: donation shape-compatible under the static gate (tools/lint.py).
_accum_moments = donating_jit(_accum_moments_impl, donate_argnums=(0, 1),
                              probe=_moments_probe)


from ...workflow.transformer import HostTransformer  # noqa: E402


class TermFrequency(HostTransformer):
    """Seq of terms -> seq of (unique term, weighting(count)) pairs
    (reference ``stats/TermFrequency.scala:20-22``). A host-stage node;
    output order is first appearance, deterministically.
    """

    def __init__(self, fun=None):
        self.fun = fun or (lambda x: x)

    def eq_key(self):
        return (TermFrequency, self.fun)

    def apply(self, terms):
        counts = {}
        order = []
        for t in terms:
            key = tuple(t) if isinstance(t, list) else t
            if key not in counts:
                counts[key] = 0
                order.append(key)
            counts[key] += 1
        return [(k, float(self.fun(counts[k]))) for k in order]

