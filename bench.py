"""Benchmark harness: RandomPatchCifar featurization + solve throughput.

Measures end-to-end images/sec/chip for the north-star pipeline
(Convolver -> SymmetricRectifier -> Pooler -> vectorize -> linear model)
at a realistic configuration (1024 filters, 6x6 patches, 14/13 pooling) on
whatever accelerator is attached. Prints ONE JSON line:
{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline is measured throughput / 10_000 images/sec/chip — the
BASELINE.json north-star target for v5e.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def build_bench(num_filters=1024, patch_size=6, alpha=0.25):
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import (
        fused_cifar_featurize,
        use_pallas,
    )

    rng = np.random.RandomState(0)
    filters = rng.randn(num_filters, patch_size * patch_size * 3).astype(np.float32)
    w = rng.randn(num_filters * 2 * 2 * 2, 10).astype(np.float32) * 0.01
    b = rng.randn(10).astype(np.float32)

    if use_pallas():
        # fused Pallas featurization: conv/rectify/pool stay in VMEM
        @jax.jit
        def featurize_and_predict(imgs):
            feats = fused_cifar_featurize(
                imgs, jnp.asarray(filters), 32, patch_size, 3, 13, 14,
                10.0, alpha)
            return jnp.argmax(feats @ w + b, axis=-1)

        return featurize_and_predict

    @jax.jit
    def featurize_and_predict(imgs):
        def one(img):
            conv = filter_bank_convolve(
                img, jnp.asarray(filters), patch_size, 3, True,
                None, 10.0,
            )
            pos = jnp.maximum(0.0, conv - alpha)
            neg = jnp.maximum(0.0, -conv - alpha)
            r = jnp.concatenate([pos, neg], axis=-1)
            pooled = pool_image(r, 13, 14, "identity", "sum")
            return pooled.reshape(-1)

        feats = jax.vmap(one)(imgs)
        return jnp.argmax(feats @ w + b, axis=-1)

    return featurize_and_predict


def solver_bench():
    """Optional second metric (BASELINE: "block-LS solver TFLOPS"):
    one-pass BCD at CIFAR-scale (n=50k, d=8192 in 4096 blocks, k=10)."""
    import functools
    import time as _time

    from keystone_tpu.ops import linalg

    rng = np.random.default_rng(0)
    n, d, k, bs = 50_000, 8192, 10, 4096
    # generate per-block directly in f32: avoids a 3 GB f64 host
    # intermediate and keeps only the block buffers on device
    blocks = tuple(
        jnp.asarray(rng.standard_normal((n, bs), dtype=np.float32))
        for _ in range(d // bs))
    Y = jnp.asarray(rng.standard_normal((n, k), dtype=np.float32))
    run = jax.jit(functools.partial(linalg.bcd_core, num_passes=1))
    [np.asarray(o) for o in run(blocks, Y, jnp.float32(0.1))]
    iters = 5
    t0 = _time.perf_counter()
    for _ in range(iters):
        out = run(blocks, Y, jnp.float32(0.1))
    [np.asarray(o) for o in out]
    dt = (_time.perf_counter() - t0) / iters
    flops = sum(
        2 * n * A.shape[1] ** 2 + A.shape[1] ** 3 / 3 + 4 * n * A.shape[1] * k
        for A in blocks)
    print(json.dumps({
        "metric": "block_ls_solver_tflops",
        "value": round(flops / dt / 1e12, 2),
        "unit": "TFLOPS",
        "vs_baseline": round(flops / dt / 1e12 / 45.0, 4),  # ~f32 MXU peak
    }))


def main():
    n_dev = len(jax.devices())
    batch = 1024
    imgs = np.random.RandomState(1).rand(batch, 32, 32, 3).astype(np.float32) * 255
    imgs = jax.device_put(imgs)

    fn = build_bench()
    # warmup / compile; np.asarray forces a full host sync (the axon
    # platform's block_until_ready can return before execution completes)
    np.asarray(fn(imgs))
    np.asarray(fn(imgs))

    iters = 10
    start = time.perf_counter()
    for _ in range(iters):
        out = fn(imgs)
    np.asarray(out)
    elapsed = time.perf_counter() - start

    images_per_sec = batch * iters / elapsed
    per_chip = images_per_sec / n_dev
    print(
        json.dumps(
            {
                "metric": "cifar_randompatch_images_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / 10000.0, 4),
            }
        )
    )


if __name__ == "__main__":
    import sys

    if "--solver" in sys.argv:
        solver_bench()
    else:
        main()
