"""Benchmark harness for the north-star RandomPatchCifar pipeline.

Default invocation emits ONE JSON line PER METRIC
(``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``):

1. ``cifar_randompatch_images_per_sec_per_chip`` — featurization
   throughput (Convolver -> SymmetricRectifier -> Pooler -> classify) at
   the reference config (1024 filters, 6x6 patches, 14/13 pooling).
   vs_baseline = value / 10_000 (the BASELINE.json v5e north star).
2. ``cifar_e2e_images_per_sec_per_chip`` — END-TO-END throughput
   including the solve: featurize the train set, fit the
   BlockLeastSquares model (blockSize 4096), featurize + predict the
   test set. vs_baseline = value / 10_000.
3. ``block_ls_solver_tflops`` — one-pass BCD at CIFAR scale (n=50k,
   d=8192, blockSize 4096), HIGHEST-precision f32 GEMMs (the reference
   solved in f64). vs_baseline = value / 33 (~achievable peak at that
   precision: bf16 peak / 6 passes).
4. ``cifar_randompatch_test_error`` — test error of the REAL
   RandomPatchCifar app (full DAG: patch whitening, fused featurizer,
   StandardScaler, BlockLeastSquares, MaxClassifier). Runs on real
   CIFAR-10 when a binary copy is found ($CIFAR10_DIR or common paths);
   otherwise on a procedurally generated surrogate at CIFAR shapes,
   flagged by the extra "dataset" key. vs_baseline = 0.16 / value
   (>1 means better than the ~84% published-accuracy bar).

Plus per-app benches covering the rest of BASELINE.md's benchmark
configs: ``imagenet_rehearsal_images_per_sec_per_chip`` (SIFT->PCA->FV +
1000-class weighted solve at VGA shapes),
``mnist_random_fft_images_per_sec_per_chip`` (4 FFT branches, blockSize
2048) and ``timit_frames_per_sec_per_chip`` (8x4096 cosine features, 147
classes), each through the real app DAG on synthetic data with the
test error recorded in the metric line.

Streaming-ingest sections (``parallel/streaming.py``):
``tar_loader_sift_streamed_images_per_sec`` measures the tar -> decode
-> device -> SIFT path with the double-buffered prefetcher against the
serial path, and ``cifar_streamed_e2e_images_per_sec_per_chip`` runs the
out-of-core CIFAR fit (per-chunk featurize -> Gram/cross accumulate ->
finalize) under an asserted HBM ingest budget.

``--solver``/``--featurize``/``--e2e``/``--imagenet``/``--mnist``/
``--timit``/``--newsgroups``/``--accuracy``/``--streamed-e2e`` run a
single section (``newsgroups_docs_per_sec`` covers the BASELINE text
config: bigrams + binary TF + CommonSparseFeatures 100k + NaiveBayes).
``KEYSTONE_BENCH_SMALL=1`` shrinks sizes for CPU smoke-testing.

Budgeting: per-section durations measured on this host persist in
``.bench_durations.json``; over-budget sections SHRINK (scaled n/reps,
``"scaled"`` key on their metric lines) instead of being skipped, so
every historical metric appears in every artifact.
"""
from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".xla_cache")


def _enable_compilation_cache():
    """Persistent XLA compilation cache (verified working through the
    axon remote-compile tunnel): a prior bench run on this host leaves
    warm executables on disk, so the driver's timed invocation spends
    its budget measuring instead of compiling (round-2 failure mode:
    the MNIST app burned 159.5 s of the budget on cold compiles).
    Called only from the CLI entry — importing bench for a helper (the
    surrogate test does) must not turn on disk-cache side effects."""
    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

SMALL = os.environ.get("KEYSTONE_BENCH_SMALL") == "1"

#: Budget scale for the CURRENT section, set by main()'s scheduler.
#: 1.0 = full size; < 1.0 = the section was admitted over budget and
#: must SHRINK (fewer reps, scaled n) instead of being skipped, so
#: every BENCH_r*.json metric appears in every round (VERDICT r5
#: weak#1). Metric lines carry a "scaled" key whenever < 1.
_SCALE = 1.0

#: Floor for shrunk sections: below this the numbers stop meaning
#: anything (pure dispatch floor), so scaling clamps here.
_MIN_SCALE = 0.2


def _scaled(n, mult=1, floor=None):
    """``n`` shrunk by the current budget scale, rounded DOWN to a
    multiple of ``mult`` (shard/batch divisibility), floored at
    ``floor`` (default one ``mult``)."""
    floor = mult if floor is None else floor
    out = int(n * _SCALE) // mult * mult
    return max(out, floor)


#: Measured per-section durations from previous runs on this host
#: (written after every section): the scheduler budgets from evidence,
#: not hardcoded estimates — stale estimates are what skipped 4-5
#: sections in r4/r5.
_DURATIONS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_durations.json")


def _load_durations() -> dict:
    """Read the persisted per-section duration estimates, validating
    them: a corrupt or hand-edited file (bad JSON, non-dict, negative /
    non-numeric / non-finite durations) is discarded with a warning and
    regenerated by the next clean runs — never allowed to crash the
    bench or poison the budget scheduler."""
    import sys

    try:
        with open(_DURATIONS_PATH) as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {}
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as exc:
        print(f"bench: discarding unreadable {_DURATIONS_PATH} "
              f"({type(exc).__name__}: {exc}); section durations will "
              "be re-measured", file=sys.stderr)
        return {}
    if not isinstance(raw, dict):
        print(f"bench: discarding {_DURATIONS_PATH} (expected a JSON "
              f"object, got {type(raw).__name__}); section durations "
              "will be re-measured", file=sys.stderr)
        return {}
    out, bad = {}, []
    for key, value in raw.items():
        ok = (isinstance(key, str)
              and isinstance(value, (int, float))
              and not isinstance(value, bool)
              and np.isfinite(value) and value > 0)
        if ok:
            out[key] = float(value)
        else:
            bad.append(key)
    if bad:
        print(f"bench: ignoring {len(bad)} invalid duration "
              f"entr{'y' if len(bad) == 1 else 'ies'} in "
              f"{_DURATIONS_PATH} ({', '.join(map(str, bad[:5]))}); "
              "those sections will be re-measured", file=sys.stderr)
    return out


def _record_duration(name: str, seconds: float) -> None:
    """Persist a section duration estimate. The write POLICY lives at
    the call sites in ``main()``: clean full-size non-SMALL runs record
    their measured wall, and budget-shrunk runs only DECAY an existing
    estimate toward their observed wall (never extrapolate a shrunk
    wall upward — mostly fixed compile/setup overhead would inflate the
    estimate and ratchet the section into permanent shrinking; SMALL
    smoke runs and retried sections never write at all)."""
    durations = _load_durations()
    durations[name] = round(seconds, 1)
    tmp = _DURATIONS_PATH + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(durations, f, indent=1, sort_keys=True)
        os.replace(tmp, _DURATIONS_PATH)
    except Exception:
        pass

#: Wall-clock budget for the full run. Round 2's driver kill (rc=124)
#: came AFTER ~910s of completed sections (featurize/solver/imagenet/
#: e2e/mnist all emitted), so the driver timeout is >~910s. 780 keeps
#: >2 minutes of margin under that bound for a section that overruns
#: its estimate after being admitted (the per-section check bounds
#: start times, not overruns), so the process always reaches its own
#: exit path and the lowest-priority sections are the ones sacrificed,
#: explicitly. A fully warm-cache run measures ~460s total.
BUDGET_S = float(os.environ.get("KEYSTONE_BENCH_BUDGET_S", "780"))
_START = time.monotonic()

FLAGSHIP = "cifar_randompatch_images_per_sec_per_chip"

_emitted = 0
_metrics: dict = {}  # metric name -> emitted line (for the summary line)
_section_buffer = None  # list while a section runs under _run_section
_scaled_sections: set = set()  # sections run at _SCALE < 1 this run


def _emit_meta():
    """Emit the ``bench_meta`` identity line: hostname, device kind,
    jax version, and which sections ran budget-shrunk. ``benchdiff``
    reads it from the artifact's stdout tail — cross-HOST comparisons
    refuse without ``--force`` (different host = different experiment),
    and shrunk sections are excluded from regression classification
    (their metric lines carry ``scaled`` keys; the list here is the
    run-level summary). Emitted at start (so a cut-short run still
    carries its identity) and again before the final summary (with the
    complete scaled-sections list)."""
    import socket

    try:
        dev = jax.devices()[0]
        device_kind, backend, n_dev = (
            dev.device_kind, dev.platform, len(jax.devices()))
    except Exception:
        device_kind = backend = "unknown"
        n_dev = 0
    print(json.dumps({"bench_meta": {
        "hostname": socket.gethostname(),
        "device_kind": device_kind,
        "backend": backend,
        "num_devices": n_dev,
        "jax_version": jax.__version__,
        "small": SMALL,
        "scaled_sections": sorted(_scaled_sections),
    }}), flush=True)


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": vs_baseline}
    if _SCALE < 1.0:
        # budget-shrunk section: the value was measured at reduced
        # size/reps — comparable only with other runs at scale 1.0
        # once the budget recovers, never silently absent
        line["scaled"] = round(_SCALE, 2)
    line.update(extra)
    if _section_buffer is not None:
        # held until the section completes: a failed attempt's partial
        # lines never reach stdout, so a retry cannot emit duplicate
        # metric lines with stale values
        _section_buffer.append(line)
    else:
        _flush_line(line)


def _flush_line(line):
    global _emitted
    print(json.dumps(line), flush=True)
    _metrics[line["metric"]] = line
    _emitted += 1


def _emit_summary():
    """Restate the flagship metric with every other section's value as
    extra keys. Called after EVERY section: the driver parses the LAST
    stdout JSON line as the headline, so whenever the run is cut short
    the headline is still the flagship with all evidence so far."""
    flag = _metrics.get(FLAGSHIP)
    if flag is None or len(_metrics) < 2:
        return
    line = dict(flag)
    line["summary"] = True
    for name, other in _metrics.items():
        if name == FLAGSHIP:
            continue
        line[name] = other["value"]
        if name == "cifar_randompatch_test_error":
            # linear_pixels_contrast_baseline must travel with the
            # error it contextualizes: without it the parsed headline
            # presents the raw-pixel 0.93 as a broken app (r4 weak#7)
            for key in ("dataset", "linear_pixels_test_error",
                        "linear_pixels_contrast_baseline"):
                if key in other:
                    line["accuracy_" + key if key == "dataset" else key] = \
                        other[key]
    print(json.dumps(line), flush=True)


def _timed_median(work, *, setup=None, reps=None, target_window=2.0,
                  max_mult=16, warmup_fence=False, compile_wall0=None):
    """Median-of-``reps`` seconds-per-call, each rep measured over a
    window of >= ``target_window`` seconds (the call repeated ``m``
    times per window when a single call is shorter).

    ``warmup_fence=True`` splits cold-compile wall out of the timed
    section via the compile observatory: the two estimate calls double
    as the warmup that drains every pending compile, the XLA compile
    wall they absorbed is reported as ``compile_s`` on the metric line
    (the un-attributed component of the documented 76-85k e2e noise
    band, now attributed), and the observatory's warmup fence is armed
    around the timed reps — any compile INSIDE them is a flagged
    unexpected recompile (``compile.unexpected_total``), not silent
    timing noise.

    Round 4's single-shot 0.2-0.5 s refit windows read tunnel jitter as
    app regressions (VERDICT r4 weak#2/next#3: mnist "-53%", tar loader
    "-47%" with no code cause); a >= 2 s window caps the dispatch-floor
    share at ~1% and the median rejects one-off executable-load stalls.
    The window multiplier comes from the MIN of two estimate calls
    (ADVICE r5 low#5: a one-off executable-load stall in a single
    unguarded estimate inflates est, collapsing m to 1 and undersizing
    every rep's window — the exact jitter this helper exists to reject).
    Returns (median_dt, evidence) where evidence carries the window
    multiplier, rep count, and rep spread for the metric line.
    Budget-shrunk sections (``_SCALE < 1``) default to 2 reps over a
    proportionally smaller window — the floor-scaled trailing sections
    must fit the margin the driver's kill window leaves."""
    if reps is None:
        reps = 3 if _SCALE >= 1.0 else 2
    if _SCALE < 1.0:
        target_window = max(0.5, target_window * _SCALE)
    obs = None
    if warmup_fence:
        from keystone_tpu.observability import compile_observatory

        obs = compile_observatory()
        # sections that warm explicitly BEFORE calling in pass the
        # observatory wall snapshotted before that warm call — without
        # it the cold compiles all land in the section's own warm-up
        # and the emitted compile_s is vacuously ~0
        if compile_wall0 is None:
            compile_wall0 = obs.wall_s_total()
    est = float("inf")
    for _ in range(2):
        if setup is not None:
            setup()
        t0 = time.perf_counter()
        work()
        est = min(est, time.perf_counter() - t0)
    m = max(1, min(max_mult, int(np.ceil(target_window / max(est, 1e-3)))))
    compile_s = None
    if obs is not None:
        # compiles are synchronous on the dispatching thread, so after
        # the estimate calls return the pending set is drained; what
        # remains is steady state and the fence makes that assertable
        compile_s = round(obs.wall_s_total() - compile_wall0, 3)
        obs.arm_fence("bench:timed")
    times = []
    try:
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(m):
                if setup is not None:
                    setup()  # host-side cache clear, microseconds
                work()
            times.append((time.perf_counter() - t0) / m)
    finally:
        if obs is not None:
            obs.disarm_fence()
    med = float(np.median(times))
    ev = {"timing_reps": reps, "timing_window_mult": m,
          "timing_spread": round((max(times) - min(times)) / med, 3)}
    if compile_s is not None:
        ev["compile_s"] = compile_s
    return med, ev


def _ingest_stall_probe(n_chunks_per_run, n_images_per_run=None):
    """Snapshot the streaming metrics and return ``share(dt)``: the
    per-run ingest stall as a fraction of ``dt`` seconds. The metrics
    accumulate across every invocation ``_timed_median`` makes
    (estimation calls + window reps), so the stall delta is normalized
    by the observed run count before dividing — the ONE home of that
    subtlety, shared by the loader and streamed-e2e sections.

    ``share.h2d_bytes_per_image()`` reads the ``streaming.h2d_bytes``
    counter delta the same normalized way: actual wire bytes shipped
    host->device per image, the number that shows dtype-on-the-wire
    working (uint8 sources ~1/4 of an f32 wire) next to the wall-time
    keys."""
    from keystone_tpu.observability import MetricsRegistry

    reg = MetricsRegistry.get_or_create()
    stall_h = reg.histogram("streaming.ingest_stall_s")
    chunks_c = reg.counter("streaming.chunks_total")
    h2d_c = reg.counter("streaming.h2d_bytes")
    stall0, chunks0, h2d0 = stall_h.total, chunks_c.value, h2d_c.value

    def _runs():
        return max(1.0, (chunks_c.value - chunks0) / n_chunks_per_run)

    def share(dt):
        return round(min(
            ((stall_h.total - stall0) / _runs()) / max(dt, 1e-9), 1.0), 3)

    def h2d_bytes_per_image():
        per_run = (h2d_c.value - h2d0) / _runs()
        return round(per_run / max(n_images_per_run or 1, 1), 1)

    share.h2d_bytes_per_image = h2d_bytes_per_image
    return share


def _fence(tree) -> None:
    """Force completion with a 4-byte scalar pull. The axon platform's
    ``block_until_ready`` can return before execution completes, so it
    CANNOT end a timed region; ``np.asarray`` of the full result would
    time the dev-tunnel d2h instead of the chip."""
    leaves = [jnp.sum(x) for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "dtype")]
    if leaves:
        float(jnp.sum(jnp.stack([x.astype(jnp.float32) for x in leaves])))


# ------------------------------------------------------- featurize bench


def build_bench(num_filters=1024, patch_size=6, alpha=0.25):
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import (
        fused_cifar_featurize,
        use_pallas,
    )

    rng = np.random.RandomState(0)
    filters = rng.randn(num_filters, patch_size * patch_size * 3).astype(np.float32)
    w = rng.randn(num_filters * 2 * 2 * 2, 10).astype(np.float32) * 0.01
    b = rng.randn(10).astype(np.float32)

    if use_pallas():
        # fused Pallas featurization: conv/rectify/pool stay in VMEM
        @jax.jit
        def featurize_and_predict(imgs):
            feats = fused_cifar_featurize(
                imgs, jnp.asarray(filters), 32, patch_size, 3, 13, 14,
                10.0, alpha)
            return jnp.argmax(feats @ w + b, axis=-1)

        return featurize_and_predict

    @jax.jit
    def featurize_and_predict(imgs):
        def one(img):
            conv = filter_bank_convolve(
                img, jnp.asarray(filters), patch_size, 3, True,
                None, 10.0,
            )
            pos = jnp.maximum(0.0, conv - alpha)
            neg = jnp.maximum(0.0, -conv - alpha)
            r = jnp.concatenate([pos, neg], axis=-1)
            pooled = pool_image(r, 13, 14, "identity", "sum")
            return pooled.reshape(-1)

        feats = jax.vmap(one)(imgs)
        return jnp.argmax(feats @ w + b, axis=-1)

    return featurize_and_predict


def featurize_bench():
    n_dev = len(jax.devices())
    batch = 256 if SMALL else 1024
    iters = 3 if SMALL else _scaled(64, mult=4, floor=8)
    imgs = jax.device_put(
        (np.random.RandomState(1).rand(batch, 32, 32, 3) * 255)
        .astype(np.float32))

    one = build_bench(num_filters=128 if SMALL else 1024)

    # all iterations in ONE dispatch (a Python loop of per-batch
    # dispatches measures the dev-tunnel round-trip, not the
    # featurizer), over ONE uploaded batch perturbed per iteration —
    # the +i keeps the loop body iteration-dependent so XLA cannot
    # hoist the featurization out of the lax.map
    @jax.jit
    def fn(b):
        return jax.lax.map(
            lambda i: one(b + i), jnp.arange(iters, dtype=jnp.float32))

    _fence(fn(imgs))  # warmup / compile
    _fence(fn(imgs))

    start = time.perf_counter()
    out = fn(imgs)
    _fence(out)
    elapsed = time.perf_counter() - start

    per_chip = batch * iters / elapsed / n_dev
    _emit("cifar_randompatch_images_per_sec_per_chip", round(per_chip, 1),
          "images/sec/chip", round(per_chip / 10000.0, 4))


# ------------------------------------------------------------ e2e bench


def e2e_bench():
    """Featurize + SOLVE + predict, the number VERDICT r1 asked for.

    Everything device-resident end to end: batches are uploaded once
    before timing (on production hosts that's a PCIe copy overlapped
    with compute; on the tunneled bench chip the link runs at single-
    digit MB/s and would swamp the measurement), features stay on
    device, the block solve consumes the device-resident feature matrix,
    and prediction reduces to class ids before the final host sync.
    """
    from keystone_tpu.ops.pallas_kernels import (
        fused_cifar_featurize,
        use_pallas,
    )
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image

    n_dev = len(jax.devices())
    num_filters = 128 if SMALL else 1024
    patch = 6
    batch = 512 if SMALL else 2_048
    n_train = 2_048 if SMALL else _scaled(20_480, mult=batch, floor=2 * batch)
    n_test = 512 if SMALL else _scaled(4_096, mult=batch, floor=batch)

    rng = np.random.RandomState(2)
    filters = rng.randn(num_filters, patch * patch * 3).astype(np.float32)

    if use_pallas():
        @jax.jit
        def featurize(imgs):
            return fused_cifar_featurize(
                imgs, jnp.asarray(filters), 32, patch, 3, 13, 14, 10.0, 0.25)
    else:
        @jax.jit
        def featurize(imgs):
            def one(img):
                conv = filter_bank_convolve(
                    img, jnp.asarray(filters), patch, 3, True, None, 10.0)
                pos = jnp.maximum(0.0, conv - 0.25)
                neg = jnp.maximum(0.0, -conv - 0.25)
                return pool_image(
                    jnp.concatenate([pos, neg], -1), 13, 14, "identity", "sum"
                ).reshape(-1)

            return jax.vmap(one)(imgs)

    y_tr = rng.randint(0, 10, n_train)
    L_host = (-np.ones((n_train, 10)) + 2.0 * np.eye(10)[y_tr]).astype(np.float32)

    # images generated ON DEVICE (throughput content is irrelevant): a
    # host-generated ~300 MB stack rode the dev tunnel, whose bandwidth
    # swings put 60..500 s of pure upload into this section (the round-3
    # driver-sim run that blew the budget); only the small label matrix
    # is uploaded. Batches sharded over the data axis so dividing by
    # device count below is earned on multi-chip hosts.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel.mesh import make_mesh

    import functools

    sh = NamedSharding(make_mesh(jax.devices()), P(None, "data"))

    @functools.partial(jax.jit, static_argnames=("n",), out_shardings=sh)
    def gen_images(key, n):
        assert n % batch == 0, (  # silent // truncation would inflate
            f"{n} images not divisible by batch {batch}")  # the metric
        return 255.0 * jax.random.uniform(
            key, (n // batch, batch, 32, 32, 3), jnp.float32)

    train_dev = gen_images(jax.random.PRNGKey(3), n_train)
    test_dev = gen_images(jax.random.PRNGKey(4), n_test)
    L = jax.device_put(L_host, NamedSharding(sh.mesh, P("data")))
    _fence((train_dev, test_dev, L))  # staging fence, untimed

    # the whole train path (featurize every batch -> center -> BCD
    # solve) stages into ONE jit, and prediction into another: the
    # estimator's own staged solve core (block_least_squares), without a
    # dev-tunnel round-trip per batch. lax.map featurizes batch-at-a-
    # time so HBM holds one batch of conv activations, not all of them.
    from keystone_tpu.nodes.learning.linear import block_least_squares

    F = num_filters * 2 * 2 * 2
    bounds = tuple((i, min(F, i + 4096)) for i in range(0, F, 4096))

    @jax.jit
    def train_step(imgs_stacked, L):
        feats = jax.lax.map(featurize, imgs_stacked)
        X = feats.reshape(n_train, F)
        Ws, x_mean, y_mean = block_least_squares(
            X, L, n_train, 0.1, bounds, 1)
        return jnp.concatenate(list(Ws), axis=0), x_mean, y_mean

    @jax.jit
    def predict_all(imgs_stacked, W, x_mean, y_mean):
        f = jax.lax.map(featurize, imgs_stacked)
        return jnp.argmax(
            (f.reshape(-1, F) - x_mean) @ W + y_mean, axis=-1)

    def fit_and_predict():
        W, x_mean, y_mean = train_step(train_dev, L)
        return np.asarray(predict_all(test_dev, W, x_mean, y_mean))

    # warm EVERYTHING outside the timed region (featurize, the solver's
    # _block_solve at full shapes, predict) — steady-state throughput is
    # the metric; XLA compiles once per shape
    from keystone_tpu.observability import compile_observatory

    compile_wall0 = compile_observatory().wall_s_total()
    fit_and_predict()

    elapsed, ev = _timed_median(fit_and_predict, warmup_fence=True,
                                compile_wall0=compile_wall0)
    per_chip = (n_train + n_test) / elapsed / n_dev
    _emit("cifar_e2e_images_per_sec_per_chip", round(per_chip, 1),
          "images/sec/chip", round(per_chip / 10000.0, 4), **ev)


# --------------------------------------------------------- solver bench


def solver_bench():
    """BASELINE: "block-LS solver TFLOPS" — one-pass BCD at CIFAR scale
    (n=50k, d=8192 in 4096 blocks, k=10)."""
    import functools

    from keystone_tpu.ops import linalg

    n, d, k, bs = (5_000, 1024, 10, 512) if SMALL else (50_000, 8192, 10, 4096)
    # generate ON DEVICE: a host-generated 800 MB block would spend
    # minutes in the dev tunnel's single-digit-MB/s upload path and eat
    # the driver's whole bench budget (content is irrelevant here)
    keys = jax.random.split(jax.random.PRNGKey(0), d // bs + 1)
    blocks = tuple(
        jax.random.normal(keys[i], (n, bs), jnp.float32)
        for i in range(d // bs))
    Y = jax.random.normal(keys[-1], (n, k), jnp.float32)
    _fence((blocks, Y))  # staging fence, untimed
    run = jax.jit(functools.partial(linalg.bcd_core, num_passes=1))
    _fence(run(blocks, Y, jnp.float32(0.1)))
    iters = 2 if SMALL else _scaled(5, floor=2)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run(blocks, Y, jnp.float32(0.1))
    _fence(out)
    dt = (time.perf_counter() - t0) / iters
    flops = sum(
        2 * n * A.shape[1] ** 2 + A.shape[1] ** 3 / 3 + 4 * n * A.shape[1] * k
        for A in blocks)
    # TPU-calibrated auto-solver evidence (VERDICT r4 next#4): the
    # shipped cost-model weights must pick the solver measured fastest
    # on this chip (block_ls at every solver-bench shape; calibration
    # agreement 3/3 — tools/calibrate_cost_model.py)
    from keystone_tpu.nodes.learning import (
        BlockLeastSquaresEstimator,
        LeastSquaresEstimator,
    )
    from keystone_tpu.parallel.dataset import ArrayDataset

    rng_s = np.random.RandomState(0)
    tiny = ArrayDataset.from_numpy(rng_s.rand(8, d).astype(np.float32))
    tiny_l = ArrayDataset.from_numpy(rng_s.rand(8, k).astype(np.float32))
    pick = LeastSquaresEstimator().optimize(
        tiny, tiny_l, n=n, num_machines=1).node
    # solver GEMMs run at HIGHEST f32 precision (6 bf16 MXU passes;
    # reference solvers were f64) — achievable peak is ~bf16_peak/6
    _emit("block_ls_solver_tflops", round(flops / dt / 1e12, 2), "TFLOPS",
          round(flops / dt / 1e12 / 33.0, 4),
          auto_solver_tpu_choice=type(pick).__name__,
          auto_solver_choice_matches_measured=isinstance(
              pick, BlockLeastSquaresEstimator))


# ------------------------------------------------------- accuracy bench


def find_real_cifar10():
    """Binary CIFAR-10 (data_batch_*.bin + test_batch.bin) under
    $CIFAR10_DIR or common locations; None if absent."""
    import glob

    candidates = [os.environ.get("CIFAR10_DIR", "")]
    candidates += [
        "/root/data/cifar-10-batches-bin", "/root/data/cifar10",
        "/data/cifar-10-batches-bin", "/data/cifar10",
        "./data/cifar-10-batches-bin", "/tmp/cifar-10-batches-bin",
    ]
    for base in candidates:
        if not base or not os.path.isdir(base):
            continue
        train = sorted(glob.glob(os.path.join(base, "data_batch_*.bin")))
        test = os.path.join(base, "test_batch.bin")
        if len(train) == 5 and os.path.exists(test):
            return train, test
    return None


def make_surrogate_cifar(n_train, n_test, seed=0):
    """Discriminative surrogate at CIFAR shapes, the honest stand-in
    when the real dataset is absent (zero-egress image); flagged in the
    metric line.

    Built so featurization quality is what the accuracy measures: the
    10 classes come in 5 pairs SHARING a smooth low-frequency base (so
    raw-pixel linear models confuse the pair) and differing in
    high-frequency texture (what whitened random patch filters pick
    up). Images are shifted crops with gain jitter + heavy noise."""
    rng = np.random.RandomState(seed)
    smooth = rng.rand(5, 48, 48, 3).astype(np.float32)
    for _ in range(6):
        smooth = (smooth + np.roll(smooth, 1, 1) + np.roll(smooth, 1, 2)
                  + np.roll(smooth, -1, 1) + np.roll(smooth, -1, 2)) / 5.0
    def sharpen(t):
        return t - (np.roll(t, 1, 1) + np.roll(t, 1, 2)
                    + np.roll(t, -1, 1) + np.roll(t, -1, 2)) / 4.0

    # pair members share MOST of their texture too: only the 0.45-scaled
    # class-specific component separates them, so the task sits in an
    # informative error range (a numerics regression in featurization
    # visibly moves the metric) instead of saturating at 0
    shared = sharpen(rng.rand(5, 48, 48, 3).astype(np.float32))
    own = sharpen(rng.rand(10, 48, 48, 3).astype(np.float32))
    texture = shared[np.arange(10) // 2] + 0.45 * own
    base = smooth[np.arange(10) // 2] + 0.9 * texture
    base = (base - base.min()) / (base.max() - base.min()) * 255.0

    def split(n, r, off):
        # train and test crop from DISJOINT offset ranges, so test
        # accuracy requires the shift-invariance the conv+pool
        # featurizer provides (and raw pixels lack) — not memorization
        # of a finite crop set
        y = r.randint(0, 10, n)
        dx, dy = off + r.randint(0, 8, n), off + r.randint(0, 8, n)
        imgs = np.empty((n, 32, 32, 3), np.float32)
        for i in range(n):
            crop = base[y[i], dy[i]:dy[i] + 32, dx[i]:dx[i] + 32]
            gain = 0.7 + 0.6 * r.rand()
            imgs[i] = np.clip(
                crop * gain + 24.0 * r.randn(32, 32, 3), 0, 255)
        return imgs, y

    tr = split(n_train, np.random.RandomState(seed + 1), 0)
    te = split(n_test, np.random.RandomState(seed + 2), 8)
    return tr, te


def accuracy_bench():
    from keystone_tpu.loaders.cifar_loader import cifar_loader
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.pipelines.images.cifar.random_patch_cifar import (
        RandomCifarConfig,
        run,
    )

    real = find_real_cifar10()
    if real is not None:
        train_files, test_file = real
        train = cifar_loader(os.path.dirname(train_files[0]) + "/data_batch_*.bin")
        test = cifar_loader(test_file)
        dataset = "cifar10"
        num_filters = 1024
    else:
        (tr_x, tr_y), (te_x, te_y) = make_surrogate_cifar(
            1_024 if SMALL else _scaled(10_240, mult=512, floor=4_096),
            256 if SMALL else _scaled(2_048, mult=256, floor=1_024))
        train = LabeledData(ArrayDataset.from_numpy(tr_x),
                            ArrayDataset.from_numpy(tr_y.astype(np.int32)))
        test = LabeledData(ArrayDataset.from_numpy(te_x),
                           ArrayDataset.from_numpy(te_y.astype(np.int32)))
        dataset = "surrogate"
        num_filters = 64 if SMALL else 512

    config = RandomCifarConfig(num_filters=num_filters, lam=10.0, seed=0)
    _, _, test_eval = run(config, train=train, test=test)
    err = float(test_eval.total_error)
    extra = dict(dataset=dataset, num_filters=num_filters)
    if dataset == "surrogate":
        # context: the raw-pixel linear baseline on the same data — the
        # surrogate is built so patch-conv featurization beats it by a
        # wide margin; a numerics regression in the pipeline collapses
        # the gap
        from keystone_tpu.pipelines.images.cifar.linear_pixels import (
            run as run_linear,
            LinearPixelsConfig,
        )

        _, _, lin_eval = run_linear(
            LinearPixelsConfig(lam=10.0), train=train, test=test)
        extra["linear_pixels_test_error"] = round(
            float(lin_eval.total_error), 4)
        # VERDICT r3 weak #5: this number is near-random BY DESIGN (the
        # surrogate is constructed so raw pixels fail); flag it so a
        # reader of BENCH_r*.json doesn't mistake it for a broken app
        extra["linear_pixels_contrast_baseline"] = True
    _emit("cifar_randompatch_test_error", round(err, 4), "test error",
          round(0.16 / max(err, 1e-4), 4), **extra)


# ------------------------------------------------ TIMIT / MNIST configs


def _clear_prefix_state():
    """Drop cross-pipeline prefix-cache state so a timed rerun of an app
    actually refits instead of reusing the warm run's fitted results."""
    from keystone_tpu.workflow.env import PipelineEnv

    PipelineEnv.get_or_create().clear_state()


def timit_bench():
    """TIMIT at the reference scale defaults (BASELINE.md: 50 x 4096
    cosine random features over 440-dim inputs, 147 classes,
    TimitPipeline.scala:24-35): featurize + one-epoch block solve +
    predict, frames/sec/chip, everything device-resident. No published
    baseline; vs_baseline against a 10k frames/sec/chip strawman."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.loaders.timit import TimitFeaturesData
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.pipelines.speech.timit import TimitConfig, run

    n_dev = len(jax.devices())
    # 16k x 32k features = 2.1 GB; the centered solver copy + warm-run
    # remnants must co-exist in HBM on the single bench chip
    n_train = 2_048 if SMALL else _scaled(16_384, mult=1_024, floor=4_096)
    n_test = 512 if SMALL else _scaled(2_048, mult=512, floor=1_024)
    num_cosines = 2 if SMALL else 8     # branches of 4096 features
    k, d = 147, 440

    rng = np.random.RandomState(0)
    protos = rng.randn(k, d).astype(np.float32)  # class prototypes

    def split(n, seed):
        # noise sized for genuine class overlap (||proto_i - proto_j||
        # ~ sqrt(2d) ~ 29.7, sigma 4.0 (3.0 saturated to 0.5% error at full size)
        # sigma across 146 competitors): the Bayes error is nonzero and
        # train-size-independent, so the emitted test_error cannot
        # saturate at 0.00% at full scale (VERDICT r2 weak#3) — real
        # TIMIT phone classification sits near ~33% error itself
        r = np.random.RandomState(seed)
        y = r.randint(0, k, n)
        X = (protos[y] + 4.0 * r.randn(n, d)).astype(np.float32)
        return LabeledData(ArrayDataset.from_numpy(X),
                           ArrayDataset.from_numpy(y.astype(np.int32)))

    data = TimitFeaturesData(train=split(n_train, 1), test=split(n_test, 2))
    # gamma matched to the synthetic feature scale (||x-x'||^2 ~ 2d);
    # the app default 0.0555 is calibrated for real TIMIT features
    config = TimitConfig(num_cosines=num_cosines, num_epochs=1, lam=1e-2,
                         gamma=1.0 / (2 * d))

    run(config, data=data)  # warm: DAG tracing + XLA compiles
    import gc

    gc.collect()            # release the warm run's HBM before refitting
    result = {}

    def refit():
        result["eval"] = run(config, data=data)[1]

    dt, ev = _timed_median(refit, setup=_clear_prefix_state)
    per_chip = (n_train + n_test) / dt / n_dev
    _emit("timit_frames_per_sec_per_chip", round(per_chip, 1),
          "frames/sec/chip", round(per_chip / 10_000.0, 4),
          num_cosine_features=num_cosines * 4096,
          test_error=round(float(result["eval"].total_error), 4), **ev)


def mnist_bench():
    """MnistRandomFFT at the README example scale (4 FFT branches,
    blockSize 2048, BASELINE.md): images/sec/chip through the real app
    DAG on synthetic MNIST-shaped data."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.pipelines.images.mnist.random_fft import (
        MnistRandomFFTConfig,
        run,
    )

    n_dev = len(jax.devices())
    n_train = 2_048 if SMALL else _scaled(16_384, mult=1_024, floor=4_096)
    n_test = 512 if SMALL else _scaled(2_048, mult=512, floor=1_024)

    rng = np.random.RandomState(0)
    # tight prototypes under 0.35 noise so the task has genuine overlap
    # (the old wide U[0,1] protos saturated test_error at 0.00% at full
    # train scale, VERDICT r2 weak#3). The 0.18 spread is empirical:
    # [0,1] clipping plus the sign->FFT->rectify featurization loses
    # enough margin that the full-size Bayes floor is real
    # (0.18 and 0.10 both saturated to 0.0 at the full 16384-example size; pairwise discriminant ~2.8 sigma at 0.05); the full-size value is what is checked
    # non-saturated on the bench chip.
    protos = (0.5 + 0.05 * rng.randn(10, 784)).astype(np.float32)

    def split(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 10, n)
        X = np.clip(protos[y] + 0.35 * r.randn(n, 784), 0, 1).astype(
            np.float32)
        return LabeledData(ArrayDataset.from_numpy(X),
                           ArrayDataset.from_numpy(y.astype(np.int32)))

    train, test = split(n_train, 1), split(n_test, 2)
    config = MnistRandomFFTConfig(num_ffts=4, block_size=2048, lam=1e-2)

    run(config, train=train, test=test)  # warm: DAG tracing + XLA compiles
    result = {}

    def refit():
        result["eval"] = run(config, train=train, test=test)[2]

    dt, ev = _timed_median(refit, setup=_clear_prefix_state)
    per_chip = (n_train + n_test) / dt / n_dev
    _emit("mnist_random_fft_images_per_sec_per_chip", round(per_chip, 1),
          "images/sec/chip", round(per_chip / 10_000.0, 4),
          test_error=round(float(result["eval"].total_error), 4), **ev)


def newsgroups_bench():
    """NewsgroupsPipeline at the reference featurization config
    (BASELINE.md: bigrams + binary TermFrequency + CommonSparseFeatures
    100k + NaiveBayes, NewsgroupsPipeline.scala:24-31) on a synthetic
    20-class corpus: docs/sec through the real app DAG. The featurizer
    and the sparse NaiveBayes fit are host-stage (tokenize/ngram/count —
    CPU-bound in the reference's Spark executors too); scoring runs as
    the padded-COO device einsum. No published baseline; vs_baseline
    against a 1k docs/sec strawman.
    """
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
    from keystone_tpu.pipelines.text.newsgroups import (
        NewsgroupsConfig,
        run,
    )

    n_classes = 20
    n_train = 512 if SMALL else _scaled(4_096, mult=256, floor=1_024)
    n_test = 128 if SMALL else _scaled(1_024, mult=128, floor=256)
    words_per_doc = 40

    rng = np.random.RandomState(0)
    # class vocabularies drawn from a SHARED sliding window — adjacent
    # classes overlap in half their discriminative words, and the
    # per-doc count of own-class words is random (binomial, sometimes
    # zero), so neighbor confusion is irreducible and the emitted
    # test_error cannot saturate at 0.00% (VERDICT r2 weak#3)
    common = [f"word{i}" for i in range(2_000)]
    class_vocab = [
        [f"g{(c * 25 + i) % (n_classes * 25)}" for i in range(50)]
        for c in range(n_classes)
    ]

    def corpus(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, n_classes, n)
        docs = []
        for i in range(n):
            own = r.choice(class_vocab[y[i]], r.binomial(words_per_doc // 4, 0.6))
            noise = r.choice(common, words_per_doc - len(own))
            words = np.concatenate([own, noise])
            r.shuffle(words)
            docs.append(" ".join(words))
        return LabeledData(
            data=HostDataset(docs),
            labels=ArrayDataset.from_numpy(y.astype(np.int32)),
        )

    train, test = corpus(n_train, 1), corpus(n_test, 2)
    config = NewsgroupsConfig(n_grams=2, common_features=100_000)

    run(config, train=train, test=test, num_classes=n_classes)  # warm
    result = {}

    def refit():
        result["eval"] = run(config, train=train, test=test,
                             num_classes=n_classes)[1]

    dt, ev = _timed_median(refit, setup=_clear_prefix_state)
    per_sec = (n_train + n_test) / dt
    _emit("newsgroups_docs_per_sec", round(per_sec, 1), "docs/sec",
          round(per_sec / 1_000.0, 4),
          test_error=round(float(result["eval"].total_error), 4), **ev)


def amazon_bench():
    """AmazonReviewsPipeline (reference
    AmazonReviewsPipeline.scala:25-33: bigrams + binary TermFrequency +
    CommonSparseFeatures 100k + logistic regression) on a synthetic
    sentiment corpus: docs/sec through the real app DAG. Sentiment words
    are drawn from overlapping positive/negative windows with random
    per-doc counts, so the emitted accuracy cannot saturate. No published
    baseline; vs_baseline against the same 1k docs/sec strawman as
    newsgroups."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
    from keystone_tpu.pipelines.text.amazon_reviews import (
        AmazonReviewsConfig,
        run,
    )

    n_train = 512 if SMALL else _scaled(4_096, mult=256, floor=1_024)
    n_test = 128 if SMALL else _scaled(1_024, mult=128, floor=256)
    words_per_doc = 40
    common = [f"word{i}" for i in range(2_000)]
    # two overlapping 60-word sentiment windows over a shared 90-word
    # affect vocabulary: 30 words are ambiguous
    affect = [f"s{i}" for i in range(90)]
    polarity_vocab = [affect[:60], affect[30:]]

    def corpus(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 2, n)
        docs = []
        for i in range(n):
            own = r.choice(polarity_vocab[y[i]],
                           r.binomial(words_per_doc // 4, 0.6))
            noise = r.choice(common, words_per_doc - len(own))
            words = np.concatenate([own, noise])
            r.shuffle(words)
            docs.append(" ".join(words))
        return LabeledData(
            data=HostDataset(docs),
            labels=ArrayDataset.from_numpy(y.astype(np.int32)),
        )

    train, test = corpus(n_train, 1), corpus(n_test, 2)
    config = AmazonReviewsConfig(n_grams=2, common_features=100_000,
                                 num_iters=10)
    run(config, train=train, test=test)  # warm
    _clear_prefix_state()
    t0 = time.perf_counter()
    _, ev = run(config, train=train, test=test)
    dt = time.perf_counter() - t0
    per_sec = (n_train + n_test) / dt
    _emit("amazon_docs_per_sec", round(per_sec, 1), "docs/sec",
          round(per_sec / 1_000.0, 4),
          test_error=round(float(ev.error), 4))


def stupid_backoff_bench():
    """StupidBackoffPipeline (reference StupidBackoffPipeline.scala:
    31-45: tokenize -> frequency-encode -> 2..n-grams -> counts ->
    Stupid Backoff LM) on a synthetic Zipf-ish corpus: scored ngrams/sec
    through the real app. Host-stage by design (the reference's is a
    Spark shuffle job); vs_baseline against a 100k ngrams/sec strawman."""
    from keystone_tpu.parallel.dataset import HostDataset
    from keystone_tpu.pipelines.nlp.stupid_backoff_pipeline import (
        StupidBackoffConfig,
        run,
    )

    n_lines = 400 if SMALL else _scaled(4_000, mult=100, floor=1_000)
    words_per_line = 20
    rng = np.random.RandomState(0)
    # Zipf-ish unigram law over a 5k vocabulary: real backoff mass
    vocab = np.array([f"w{i}" for i in range(5_000)])
    probs = 1.0 / np.arange(1, len(vocab) + 1) ** 1.1
    probs /= probs.sum()
    lines = [
        " ".join(rng.choice(vocab, words_per_line, p=probs))
        for _ in range(n_lines)
    ]
    text = HostDataset(lines)
    config = StupidBackoffConfig(n=3)
    t0 = time.perf_counter()
    lm = run(config, text=text)
    dt = time.perf_counter() - t0
    per_sec = len(lm.scores) / dt
    _emit("stupid_backoff_ngrams_per_sec", round(per_sec, 1), "ngrams/sec",
          round(per_sec / 100_000.0, 4),
          num_ngrams=len(lm.scores), num_tokens=int(lm.num_tokens))


def voc_bench():
    """VOCSIFTFisher (reference VOCSIFTFisher.scala:42-108) on a
    synthetic multi-label set with orientation-coded classes: MAP plus
    images/sec through the full SIFT -> PCA -> FV -> BlockLS -> MAP DAG.
    Oriented stripes + heavy noise keep MAP meaningfully below 1.0. No
    published baseline; vs_baseline against the 0.59 MAP the VOC paper
    config reports in the literature (Chatfield et al. FV baseline)."""
    from keystone_tpu.loaders.image_loader_utils import MultiLabeledImage
    from keystone_tpu.parallel.dataset import HostDataset
    from keystone_tpu.pipelines.images.voc.voc_sift_fisher import (
        SIFTFisherConfig,
        run,
    )

    n_imgs = 24 if SMALL else _scaled(96, mult=8, floor=32)
    side = 96
    n_cls = 20
    rng = np.random.RandomState(0)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32)

    def make(n, seed):
        r = np.random.RandomState(seed)
        items = []
        for i in range(n):
            labels = sorted(set(r.randint(0, n_cls, r.randint(1, 3))))
            img = r.rand(side, side, 3).astype(np.float32) * 160
            for c in labels:
                ang = np.pi * c / n_cls
                stripes = np.sin((np.cos(ang) * xx + np.sin(ang) * yy)
                                 / 2.5)
                img += 45.0 * stripes[:, :, None]
            items.append(MultiLabeledImage(
                np.clip(img, 0, 255), [int(c) for c in labels],
                f"im{i}.jpg"))
        return HostDataset(items)

    train, test = make(n_imgs, 1), make(max(n_imgs // 4, 8), 2)
    config = SIFTFisherConfig(
        lam=0.5, desc_dim=32, vocab_size=8,
        num_pca_samples=int(2e5), num_gmm_samples=int(2e5), block_size=512)
    kw = dict(step=6, num_scales=3)
    run(config, train=train, test=test, sift_kwargs=kw)  # warm
    _clear_prefix_state()
    t0 = time.perf_counter()
    _, ap = run(config, train=train, test=test, sift_kwargs=kw)
    dt = time.perf_counter() - t0
    n_total = len(train) + len(test)
    vmap = float(np.mean(ap))
    _emit("voc_map", round(vmap, 4), "MAP", round(vmap / 0.59, 4),
          images_per_sec=round(n_total / dt, 2), n_images=n_total)


# -------------------------------------------- ImageNet shape rehearsal


def imagenet_rehearsal_bench():
    """VERDICT r1 next#8: drive the SIFT -> PCA -> FV -> BlockWeightedLS
    path at realistic ImageNet per-image shapes (VGA-ish pixels, ~10^4
    descriptors/image, desc_dim 64, k=16 GMM -> 2048-dim FV per branch,
    1000-class weighted solve at the combined 4096-dim FV) on synthetic
    pixels, recording a per-stage profile. Surfaces padding/bucketing
    problems the 32x32 CIFAR tests cannot (reference scale defaults:
    ``ImageNetSiftLcsFV.scala:153-174``).

    No published baseline exists for this path (BASELINE.md); vs_baseline
    is reported against a 10 images/sec/chip strawman.
    """
    from keystone_tpu.nodes.images.extractors import SIFTExtractor
    from keystone_tpu.nodes.images.fisher_vector import FisherVector
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel

    h, w = (160, 160) if SMALL else (480, 640)
    # small-mode batch must stay divisible by the 8-device CPU test mesh
    n_imgs = 8 if SMALL else 32
    desc_dim, vocab = 64, 16
    n_classes = 100 if SMALL else 1000
    fv_dim = 2 * desc_dim * vocab          # one branch
    d_solve = 2 * fv_dim                   # SIFT + LCS branches combined
    n_solve = 512 if SMALL else _scaled(4096, mult=512, floor=1_024)

    sift = SIFTExtractor(step=4, bin_size=6, num_scales=5, scale_step=1)
    n_desc = sift.descriptor_count(h, w)

    rng = np.random.RandomState(0)
    pca = jnp.asarray(rng.randn(desc_dim, 128).astype(np.float32) / 11.3)
    gmm = GaussianMixtureModel(
        means=rng.randn(desc_dim, vocab).astype(np.float32),
        variances=(0.5 + rng.rand(desc_dim, vocab)).astype(np.float32),
        weights=(np.ones(vocab) / vocab).astype(np.float32),
    )
    fv = FisherVector(gmm)

    @jax.jit
    def featurize(img_gray):
        desc = sift.apply(img_gray)                    # (128, N)
        desc = jnp.sign(desc) * jnp.sqrt(jnp.abs(desc))  # signed Hellinger
        proj = pca @ desc                              # (64, N)
        out = fv.apply(proj).reshape(-1)               # (2*64*16,)
        out = out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)
        out = jnp.sign(out) * jnp.sqrt(jnp.abs(out))
        return out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)

    imgs = rng.rand(n_imgs, h, w).astype(np.float32)
    # device-resident before timing, and ONE dispatch for the whole
    # batch (vmap): per-image dispatches would measure the dev-tunnel
    # round-trip, not the featurizer, and batching same-size images is
    # how the production path feeds the chip anyway. The batch is
    # sharded over the data axis so dividing by device count below is
    # earned on multi-chip hosts too.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel.mesh import make_mesh

    featurize_batch = jax.jit(jax.vmap(featurize))
    imgs_dev = jax.device_put(
        imgs, NamedSharding(make_mesh(jax.devices()), P("data")))
    _fence(featurize_batch(imgs_dev))                  # compile
    reps = _scaled(4, floor=2)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = featurize_batch(imgs_dev)
    _fence(out)
    feat_dt = (time.perf_counter() - t0) / reps
    per_chip = n_imgs / feat_dt / len(jax.devices())

    # batch-64 featurize via the streaming prefetcher (VERDICT r5 item
    # 3, batching half): doubled vmap batch amortizes per-dispatch
    # overhead (~+10% measured in the r5 build notes), and the host
    # feed rides the double buffer — uint8 grayscale chunks upload on
    # the prefetch thread while the chip featurizes the previous chunk,
    # so the bigger batch is actually fed. Degenerate on CPU smoke runs
    # (SMALL), honest at the rehearsal shape on chip.
    from keystone_tpu.parallel.streaming import StreamingDataset

    chunk64 = 2 * n_imgs
    n64 = 2 * chunk64
    imgs64 = (rng.rand(n64, h, w) * 255).astype(np.uint8)

    @jax.jit
    def feat_u8(X):
        return jax.vmap(featurize)(X.astype(jnp.float32) / 255.0)

    def run64():
        stream = StreamingDataset.from_numpy(
            imgs64, chunk_size=chunk64, prefetch_depth=2,
            tag="imagenet-rehearsal-64")
        outs = [feat_u8(c.data) for c in stream.chunks()]
        _fence(outs)

    run64()  # warm
    # median-of-reps like every other number here: the tunneled host's
    # ~8% between-run band would otherwise swing batch64_vs_base on a
    # single sample
    dt64, ev64 = _timed_median(run64)
    per_chip64 = n64 / dt64 / len(jax.devices())

    # 1000-class weighted solve at the combined FV dimension; warmed so
    # the metric is solver time, not XLA compile time. Inputs are staged
    # on device OUTSIDE the timed region: a fresh numpy fit would time
    # the ~5-10 MB/s dev-tunnel upload (80 MB -> ~10-15 s), not the
    # solver — the production path consumes featurizer output already
    # on device.
    from keystone_tpu.parallel.dataset import ArrayDataset

    X = rng.randn(n_solve, d_solve).astype(np.float32)
    y = rng.randint(0, n_classes, n_solve)
    L = -np.ones((n_solve, n_classes), np.float32)
    L[np.arange(n_solve), y] = 1.0
    ds_X, ds_L = ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(L)
    _fence((ds_X.data, ds_L.data))  # staging fence, untimed
    est = BlockWeightedLeastSquaresEstimator(4096, 1, 6e-5, 0.25)
    _fence(est.fit(ds_X, ds_L).weights)  # warm

    def solve():
        # completion fence only — the weights stay device-resident
        _fence(est.fit(ds_X, ds_L).weights)

    solve_dt, _ = _timed_median(solve)

    _emit("imagenet_rehearsal_images_per_sec_per_chip", round(per_chip, 2),
          "images/sec/chip", round(per_chip / 10.0, 4),
          image_shape=[h, w], descriptors_per_image=int(n_desc),
          sift_pca_fv_ms_per_image=round(1e3 * feat_dt / n_imgs, 1),
          weighted_solve_s=round(solve_dt, 2),
          solve_shape=[n_solve, d_solve, n_classes],
          batch64_images_per_sec_per_chip=round(per_chip64, 2),
          batch64_chunk=chunk64,
          batch64_vs_base=round(per_chip64 / max(per_chip, 1e-9), 3),
          batch64_timing_spread=ev64["timing_spread"],
          batch64_ingest="prefetch-depth-2-uint8")


# ----------------------------------------------- Pallas kernel program


def pallas_kernels_bench():
    """PR 13 kernel program: one bench line per kernel with an MFU
    companion, so the before/after of each kernel is denominated in
    hardware terms (PERFORMANCE.md rule 11), benchdiff-banded. Each
    section times the PRODUCTION dispatch path (``kernel_path`` names
    which implementation the dispatcher actually picked — the compiled
    Pallas kernel on TPU, the einsum fallback on CPU-sim, where these
    lines are plumbing evidence, not kernel wins) under the warmup
    fence, so a steady-state recompile in any kernel is a flagged bug,
    not timing noise.

    * ``sift_banded_images_per_sec_per_chip`` — banded-GEMM dense SIFT
      at the rehearsal image shape (vs the 502 img/s r6 VERDICT #3
      number; target >= 800 on chip).
    * ``fv_fused_images_per_sec_per_chip`` — fused GMM-posterior + FV
      at the rehearsal descriptor shape (vs a 100 img/s strawman).
    * ``predict_quantized_{f32,bf16,int8}_rows_per_sec_per_chip`` — the
      serving plane's quantized apply; vs_baseline of the narrow lines
      is the speedup over the f32 line, and each carries its parity
      evidence (argmax agreement + max relative error vs f32).
    """
    from keystone_tpu.nodes.images.fisher_vector import _fisher_vector
    from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel
    from keystone_tpu.nodes.learning.linear import (
        LinearMapper,
        _affine_apply_batch,
        _quantized_affine_batch,
    )
    from keystone_tpu.observability import compile_observatory
    from keystone_tpu.observability.compilelog import watch_jit
    from keystone_tpu.observability.utilization import UtilizationWindow
    from keystone_tpu.ops.pallas_kernels import (
        fv_fits_vmem,
        quant_fits_vmem,
        use_pallas,
    )
    from keystone_tpu.ops.sift import _resolve_kernel_mode, dense_sift

    n_dev = len(jax.devices())
    obs = compile_observatory()
    rng = np.random.RandomState(0)

    # -- banded SIFT -------------------------------------------------------
    h, w = (96, 128) if SMALL else (480, 640)
    n_imgs = 2 if SMALL else _scaled(16, mult=2, floor=4)
    sift_path = _resolve_kernel_mode(None, h, w)
    imgs = jax.device_put(rng.rand(n_imgs, h, w).astype(np.float32))
    _fence(imgs)
    sift_fn = watch_jit(jax.jit(jax.vmap(
        lambda g: dense_sift(g, 4, 6, 5, 1))), "bench_sift_banded")
    compile_wall0 = obs.wall_s_total()
    _fence(sift_fn(imgs))  # warm
    with UtilizationWindow() as uw:
        dt, ev = _timed_median(lambda: _fence(sift_fn(imgs)),
                               warmup_fence=True,
                               compile_wall0=compile_wall0)
    util = uw.report(n_devices=n_dev)
    per_chip = n_imgs / dt / n_dev
    _emit("sift_banded_images_per_sec_per_chip", round(per_chip, 2),
          "images/sec/chip", round(per_chip / 502.0, 4),
          image_shape=[h, w], kernel_path=sift_path,
          sift_banded_mfu=round(util["mfu"], 5),
          sift_banded_membw_util=round(util["membw_util"], 5),
          roofline_bound=util["bound"], **ev)

    # -- fused FV ----------------------------------------------------------
    desc_dim, vocab = 64, 16
    n_desc = 1024 if SMALL else 10_240
    fv_batch = 4 if SMALL else _scaled(16, mult=2, floor=4)
    # the REAL dispatch decision (backend AND fits-vmem), so the label
    # can never attribute a fallback measurement to the kernel
    fv_path = ("pallas" if use_pallas() and fv_fits_vmem(desc_dim, vocab)
               else "einsum")
    gmm = GaussianMixtureModel(
        means=rng.randn(desc_dim, vocab).astype(np.float32),
        variances=(0.5 + rng.rand(desc_dim, vocab)).astype(np.float32),
        weights=(np.ones(vocab) / vocab).astype(np.float32),
    )
    params = (jnp.asarray(gmm.means), jnp.asarray(gmm.variances),
              jnp.asarray(gmm.weights))
    descs = jax.device_put(
        rng.randn(fv_batch, desc_dim, n_desc).astype(np.float32))
    _fence(descs)
    fv_fn = watch_jit(jax.jit(jax.vmap(
        lambda x: _fisher_vector(x, *params, 1e-4))), "bench_fv_fused")
    compile_wall0 = obs.wall_s_total()
    _fence(fv_fn(descs))  # warm
    with UtilizationWindow() as uw:
        dt, ev = _timed_median(lambda: _fence(fv_fn(descs)),
                               warmup_fence=True,
                               compile_wall0=compile_wall0)
    util = uw.report(n_devices=n_dev)
    per_chip = fv_batch / dt / n_dev
    _emit("fv_fused_images_per_sec_per_chip", round(per_chip, 2),
          "images/sec/chip", round(per_chip / 100.0, 4),
          descriptors_per_image=n_desc, vocab=vocab,
          kernel_path=fv_path,
          fv_fused_mfu=round(util["mfu"], 5),
          fv_fused_membw_util=round(util["membw_util"], 5),
          roofline_bound=util["bound"], **ev)

    # -- quantized predict -------------------------------------------------
    n_rows = 2_048 if SMALL else _scaled(16_384, mult=2_048, floor=4_096)
    d, k = (256, 32) if SMALL else (1024, 100)
    X = rng.randn(n_rows, d).astype(np.float32)
    teacher = rng.randn(d, k).astype(np.float32) / np.sqrt(d)
    b = (rng.randn(k) * 0.01).astype(np.float32)
    X_dev = jax.device_put(X)
    _fence(X_dev)
    rates: dict = {}
    f32_out = None
    for dtype in (None, "bf16", "int8"):
        mapper = LinearMapper(teacher, intercept=b, weight_dtype=dtype)
        params_q = mapper.apply_params()
        # time the PRODUCTION batch programs — the exact jits
        # apply_dataset's map_batch dispatches (the quantized one
        # routes to the Pallas kernel on TPU when W fits VMEM)
        batch_fn = (_affine_apply_batch if dtype is None
                    else _quantized_affine_batch)
        quant_path = (
            "f32" if dtype is None
            else "pallas" if use_pallas() and quant_fits_vmem(
                d, k, params_q[0].dtype.itemsize)
            else "einsum")
        apply_fn = watch_jit(
            jax.jit(lambda xs, p=params_q, f=batch_fn: f(xs, *p)),
            f"bench_predict_{dtype or 'f32'}")
        compile_wall0 = obs.wall_s_total()
        out = np.asarray(apply_fn(X_dev))  # warm + parity evidence
        with UtilizationWindow() as uw:
            dt, ev = _timed_median(lambda: _fence(apply_fn(X_dev)),
                                   warmup_fence=True,
                                   compile_wall0=compile_wall0)
        util = uw.report(n_devices=n_dev)
        tag = dtype or "f32"
        if dtype is None:
            f32_out = out
            parity = {}
        else:
            parity = {
                "argmax_agreement_vs_f32": round(float(
                    (out.argmax(1) == f32_out.argmax(1)).mean()), 4),
                "max_rel_err_vs_f32": round(float(
                    np.abs(out - f32_out).max()
                    / max(np.abs(f32_out).max(), 1e-12)), 5),
            }
        rates[tag] = n_rows / dt / n_dev
        _emit(f"predict_quantized_{tag}_rows_per_sec_per_chip",
              round(rates[tag], 1), "rows/sec/chip",
              round(rates[tag] / max(rates["f32"], 1e-9), 4),
              solve_shape=[n_rows, d, k], kernel_path=quant_path,
              **{f"predict_quantized_{tag}_mfu": round(util["mfu"], 5),
                 f"predict_quantized_{tag}_membw_util":
                     round(util["membw_util"], 5)},
              roofline_bound=util["bound"], **parity, **ev)


# ----------------------------------------------- loader-in-the-loop bench


def serving_bench():
    """The online serving plane (``keystone_tpu/serving``): sustained
    micro-batched QPS and tail latency through the REAL request path —
    slot-gated bounded queue, pad-to-bucket coalescing, two warm
    resident models (one f32, one bf16-quantized per the PR 13 serving
    default) under an asserted HBM admission budget. The window is
    driven by the deterministic trace-replay load generator
    (``serving/loadgen.py``, PR 19) — seeded bursty arrivals, Zipf
    model popularity, mixed request sizes — instead of uniform client
    threads, so the measured tail comes from traffic-shaped load and
    the same trace replays identically across rounds; latency is
    measured per request end-to-end (enqueue -> result, the
    ``serving.request_ms`` semantics) and the compile-observatory
    fence stays armed for the whole window — a single steady-state
    recompile fails the section, because the zero-recompile invariant
    is asserted, not hoped (PERFORMANCE.md rule 14)."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability import compile_observatory
    from keystone_tpu.observability.utilization import UtilizationWindow
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.serving import ServingPlane, model_charge

    n_dev = len(jax.devices())
    d1, d2, k = (64, 96, 10) if SMALL else (256, 384, 10)
    n_fit = 512 if SMALL else _scaled(4_096, mult=512, floor=1_024)
    max_batch = 32 if SMALL else 64
    window_s = 2.0 if SMALL else float(_scaled(8, mult=1, floor=4))
    clients = 4

    def fit(d, seed, **kw):
        r = np.random.RandomState(seed)
        X = r.rand(n_fit, d).astype(np.float32)
        Y = r.rand(n_fit, k).astype(np.float32)
        return LinearMapEstimator(lam=1e-3, **kw).with_data(
            ArrayDataset.from_numpy(X),
            ArrayDataset.from_numpy(Y)).fit(), X

    f32_model, X1 = fit(d1, seed=1)
    bf16_model, X2 = fit(d2, seed=2)

    sample1 = jax.ShapeDtypeStruct((d1,), np.float32)
    sample2 = jax.ShapeDtypeStruct((d2,), np.float32)
    budget = (model_charge(f32_model, sample1, max_batch).total_nbytes()
              + model_charge(bf16_model, sample2,
                             max_batch).total_nbytes() + (1 << 20))
    plane = ServingPlane(hbm_budget=budget, max_batch=max_batch,
                         queue_depth=1024)
    plane.start()
    # snapshot AFTER the fits: compile_s on the serve line must
    # attribute the admission warmups, not the solver-fit compiles
    compile_wall0 = compile_observatory().wall_s_total()
    try:
        plane.admit("f32", f32_model, sample1, weight_dtype=None)
        plane.admit("bf16", bf16_model, sample2, weight_dtype="bf16")
        compile_s = round(
            compile_observatory().wall_s_total() - compile_wall0, 3)

        from keystone_tpu.observability import MetricsRegistry

        reg = MetricsRegistry.get_or_create()
        fill_h = reg.histogram("serving.batch_fill")
        fill_count0, fill_total0 = fill_h.count, fill_h.total
        batches0 = reg.counter("serving.batches_total").value
        rejected0 = reg.counter("serving.rejected_total").value
        # request-path tail attribution (PR 16): snapshot the phase
        # histograms the traced window will fill, so the shares below
        # cover exactly this load window
        qw_h = reg.histogram("serving.phase_ms.queue_wait")
        disp_h = reg.histogram("serving.phase_ms.dispatch")
        req_h = reg.histogram("serving.request_ms")
        qw_total0, disp_total0 = qw_h.total, disp_h.total
        req_total0 = req_h.total
        good0, bad0 = plane.slo.totals()
        rows0 = reg.counter("serving.rows_total").value
        u0 = plane.unexpected_recompiles()

        # the deterministic load window (PR 19): a seeded trace —
        # bursty arrivals, Zipf popularity across the two models,
        # mixed request sizes — replayed by closed-loop senders. The
        # schedule is oversubscribed on purpose: the senders fall
        # behind the arrival clock and drive the plane flat out, so
        # the qps line still measures capacity while the model/size
        # SEQUENCE stays identical across rounds.
        from keystone_tpu.serving.loadgen import LoadSpec, generate_trace
        from keystone_tpu.serving.loadgen import replay as replay_trace

        spec = LoadSpec(
            seed=5, duration_s=window_s, rate_rps=1500.0,
            arrival="bursty", models=("f32", "bf16"), zipf_s=1.2,
            sizes=(1, 4, 8, max_batch // 2),
            burst_mult=2.0, burst_on_s=0.5, burst_off_s=0.25)
        trace = generate_trace(spec)
        data = {"f32": X1, "bf16": X2}

        def input_for(model, n):
            return data[model][:n]

        with UtilizationWindow() as uw:
            report = replay_trace(
                trace, plane, input_for, senders=clients,
                submit_timeout_s=30.0, result_timeout_s=60.0)
        wall = report.wall_s

        unexpected = plane.unexpected_recompiles() - u0
        if unexpected:
            raise RuntimeError(
                f"{unexpected:.0f} steady-state serving recompile(s) — "
                "the zero-recompile invariant is asserted, not hoped")
        broken = (report.outcomes["error"]
                  + report.outcomes["unclassified"]
                  + report.outcomes["poisoned"])
        if broken:
            raise RuntimeError(
                f"{broken} request(s) FAILED in the fault-free bench "
                f"window: {report.errors[:4]}")
        lat_ms = np.sort(np.asarray(report.latencies_ms, np.float64))
        if lat_ms.size == 0:
            raise RuntimeError("serving window completed zero requests")
        qps_rows = (reg.counter("serving.rows_total").value
                    - rows0) / wall
        per_chip = qps_rows / n_dev
        requests_per_sec = lat_ms.size / wall
        batches = reg.counter("serving.batches_total").value - batches0
        fill_n = fill_h.count - fill_count0
        mean_fill = ((fill_h.total - fill_total0) / fill_n
                     if fill_n else None)
        util = uw.report(n_devices=n_dev)
        common = dict(
            models=2, clients=clients, window_s=round(wall, 2),
            max_batch=max_batch,
            loadgen=dict(seed=spec.seed, arrival=spec.arrival,
                         rate_rps=spec.rate_rps, zipf_s=spec.zipf_s),
            requests_per_sec=round(requests_per_sec, 1),
            batches_per_sec=round(batches / wall, 1),
            batch_fill=(None if mean_fill is None
                        else round(mean_fill, 3)),
            rejected=int(
                reg.counter("serving.rejected_total").value - rejected0),
            hbm_budget_mib=round(budget / (1 << 20), 3),
            unexpected_recompiles=0,
        )
        _emit("serve_qps_per_chip", round(per_chip, 1),
              "rows/sec/chip", round(per_chip / 10_000.0, 4),
              serve_mfu=round(util["mfu"], 6),
              serve_membw_util=round(util["membw_util"], 6),
              compile_s=compile_s, **common)
        _emit("serve_p50_ms", round(float(np.percentile(lat_ms, 50)), 3),
              "ms", round(float(np.percentile(lat_ms, 50)) / 10.0, 4),
              **common)
        _emit("serve_p99_ms", round(float(np.percentile(lat_ms, 99)), 3),
              "ms", round(float(np.percentile(lat_ms, 99)) / 10.0, 4),
              **common)

        # tail attribution (PR 16): where the request wall actually
        # went over the window — phase-ms totals over request-ms
        # totals, straight from the telescoping per-request phase
        # decomposition (queue_wait growing while dispatch holds =
        # backpressure, not the device). Phase observes are deferred
        # onto the recorder's flush path, so flush before reading.
        from keystone_tpu.observability.timeline import flight_recorder

        flight_recorder().flush()
        req_total = req_h.total - req_total0
        if req_total > 0:
            qw_share = (qw_h.total - qw_total0) / req_total
            disp_share = (disp_h.total - disp_total0) / req_total
            _emit("serve_queue_wait_share", round(qw_share, 4),
                  "share", round(qw_share / 0.5, 3), **common)
            _emit("serve_dispatch_share", round(disp_share, 4),
                  "share", round(disp_share / 0.5, 3), **common)
        # the availability the SLO tracker observed over this window
        # (delta of lifetime good/bad totals — default policy: every
        # request under 1s counts good)
        good, bad = plane.slo.totals()
        seen = (good - good0) + (bad - bad0)
        if seen > 0:
            avail = (good - good0) / seen
            _emit("serve_availability", round(avail, 6), "fraction",
                  round(avail / 0.999, 4), **common)

        # always-on overhead of the request-path plane itself
        # (PERFORMANCE.md rule 15): interleaved A/B pairs through the
        # warm plane — the OFF request runs the same path under
        # tracing_suppressed() (runtime gate, identical programs), so
        # the pair isolates the per-request latency-path cost: the
        # mint, the stamps, the reservoir offer, the defer (span
        # construction and phase observes materialize at flush points,
        # off the latency path). The interleave is REQUEST-level — each
        # pair is one traced and one suppressed request back to back,
        # order alternating — so machine drift and scheduler bursts hit
        # both streams equally (block-pair legs at this ~ms request
        # scale carry an A/A noise floor several times the 2% signal;
        # adjacent-request pairing cancels it). Deferred thunks are
        # flushed after every traced request so displaced
        # materialization is paid between timings, not inside one. The
        # estimator is the MEDIAN of the per-pair latency differences
        # over the suppressed stream's p50 — each pair's difference
        # cancels whatever the machine was doing around that pair, and
        # the median ignores the straggler/drift-scoring spikes that
        # land on single pairs, so an A/A run of this probe reads ~0
        # where comparing stream p50s still wanders by points. Banded
        # absolutely (the shared "overhead_share" marker); the bar is
        # <2%.
        from keystone_tpu.observability.reqtrace import tracing_suppressed

        probe_pairs = 300 if SMALL else 600
        probe_x = X1[:8]

        def _one(suppress):
            if suppress:
                with tracing_suppressed():
                    t0 = time.perf_counter()
                    plane.predict("f32", probe_x, timeout_s=60.0)
                    return time.perf_counter() - t0
            t0 = time.perf_counter()
            plane.predict("f32", probe_x, timeout_s=60.0)
            return time.perf_counter() - t0

        # late in a full bench run the heap is large and collector
        # pauses dwarf the ~tens-of-us signal; collect once up front
        # and hold the collector off for the probe so both streams time
        # the request path, not the allocator
        import gc

        on_lat: list = []
        off_lat: list = []
        flight_recorder().flush()
        gc.collect()
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for _ in range(16):  # warm-up pairs, discarded
                _one(False)
                _one(True)
            flight_recorder().flush()
            for i in range(probe_pairs):
                if i % 2 == 0:
                    on_lat.append(_one(False))
                    flight_recorder().flush()
                    off_lat.append(_one(True))
                else:
                    off_lat.append(_one(True))
                    on_lat.append(_one(False))
                    flight_recorder().flush()
        finally:
            if gc_was_enabled:
                gc.enable()
        if off_lat:
            diffs = sorted(o - f for o, f in zip(on_lat, off_lat))
            p50_off = sorted(off_lat)[len(off_lat) // 2]
            if p50_off > 0:
                trace_share = diffs[len(diffs) // 2] / p50_off
                _emit("serving_trace_overhead_share",
                      round(trace_share, 4), "share",
                      round(trace_share / 0.02, 3), **common)
    finally:
        plane.close()


#: chaos-soak bench lines, one gated pair per scenario. The names are
#: spelled out literally (not derived from the scenario registry) so
#: the BENCH_METRIC_NAMES catalogue test can hold them to the same
#: rename discipline as every other bench line — and so a scenario
#: silently dropped from the catalogue fails THIS section loudly
#: instead of its lines just vanishing from the artifact.
_SOAK_LINES = {
    "burst": ("soak_burst_p99_ms", "soak_burst_availability"),
    "diurnal": ("soak_diurnal_p99_ms", "soak_diurnal_availability"),
    "zipf_churn": ("soak_zipf_churn_p99_ms",
                   "soak_zipf_churn_availability"),
    "straggler_dispatch": ("soak_straggler_dispatch_p99_ms",
                           "soak_straggler_dispatch_availability"),
    "poisoned_batch": ("soak_poisoned_batch_p99_ms",
                       "soak_poisoned_batch_availability"),
    "overload_shed": ("soak_overload_shed_p99_ms",
                      "soak_overload_shed_availability"),
}


def serving_soak_bench():
    """The chaos soak (PR 19): replay each ``serving/scenarios``
    catalogue entry — deterministic loadgen trace under its seeded
    fault plan — against a fresh plane, and emit the gated pair per
    scenario: p99 of served requests (lower-better ``_ms``) and
    accepted-request availability (higher-better, the PR 16
    ``availability`` marker). vs_baseline is the scenario's own floor,
    so >1.0 on a ``_ms`` line or <1.0 on an availability line reads as
    "this round violated the chaos-gate floor". Floors are ENFORCED by
    ``tools/chaos_gate.py`` in CI; here a violation is emitted (with
    the violations named on the line), never raised — a bench round
    must record the regression, not hide the whole section."""
    from keystone_tpu.serving.scenarios import (
        SCENARIOS,
        load_catalogue,
        run_scenario,
    )

    load_catalogue()
    missing = sorted(set(_SOAK_LINES) - set(SCENARIOS))
    if missing:
        raise RuntimeError(
            f"scenario(s) {missing} dropped from the catalogue but "
            "still carry catalogued soak bench lines")
    # SMALL smoke runs keep the pair of scenarios that exercise both
    # ends of the contract (fair-weather tail + classified faults);
    # full runs soak the whole catalogue
    names = (("burst", "poisoned_batch") if SMALL
             else tuple(sorted(_SOAK_LINES)))
    for name in names:
        p99_line, avail_line = _SOAK_LINES[name]
        res = run_scenario(name, seed=0)
        extra = dict(
            scenario=name, seed=0, injections=res.injections,
            clean=res.clean,
            p99_floor_ms=res.floors.p99_ms,
            availability_floor=res.floors.availability,
            outcomes={k: int(v) for k, v in res.report.outcomes.items()})
        if not res.clean:
            extra["violations"] = res.violations
            extra["postmortem"] = res.postmortem_path
        _emit(p99_line, round(res.p99_ms, 3), "ms",
              round(res.p99_ms / res.floors.p99_ms, 4), **extra)
        _emit(avail_line, round(res.availability, 6), "fraction",
              round(res.availability / res.floors.availability, 4),
              **extra)


def fleet_bench():
    """Multi-replica serving scale-out (PR 20): the same deterministic
    loadgen workload measured twice in one section — first against a
    single saturated plane (the ``single_qps`` baseline, same
    semantics as ``serve_qps_per_chip``), then against a 3-replica
    fleet behind the ``FleetRouter`` with placement solved by the
    fleet controller under finite per-replica budgets. The fleet
    transport is IN-PROCESS (``LocalReplicaClient`` — direct plane
    calls): the section measures router + scale-out, not JSON framing;
    the real-HTTP wire path is drilled by the fleet chaos scenarios
    and ``tools/fleet_gate.py``, where correctness (not rows/sec) is
    the product.

    Placement is load-bearing: six equal-charge models FFD-spread two
    per replica, and the two Zipf-hottest are REPLICATED into the
    leftover budget (an earned solver decision — ``qps`` demand priced
    against warmup cost), so the router's depth-ordered spill can
    level the Zipf skew across replicas instead of pinning the hot
    primary. ``router_spill_share`` prices exactly that leveling
    (lower is calmer, but zero under skew means the fleet is NOT
    balancing — PERFORMANCE.md rule 19: watch the spill share, not
    just the p99).

    The comparison is throughput-at-operating-point, the serving
    scale-out claim: ONE seeded trace, replayed twice. The single
    window replays it TIME-STRETCHED by the replica count (the same
    requests, byte-identical, at the per-replica rate — it keeps up,
    so ``single_qps`` is the rows/sec one replica serves at its
    operating point); the fleet window replays it at full speed
    against the fleet. A fleet that keeps up delivers ~Nx; the 2.4x
    acceptance bar leaves room for routing overhead and placement
    imbalance. On a single-core
    CPU sim both windows share one core, so the fleet number prices
    the router/placement/spill machinery absorbing 3x the offered
    load (batch coalescing has to survive the 3-way split); on
    multi-core or TPU hosts the same section measures real parallel
    capacity.

    * ``fleet_qps`` — fleet-window rows/sec; vs_baseline is the ratio
      against 2.4x the same-run single-replica operating point (the
      PR 20 acceptance bar), so >= 1.0 reads "scale-out delivered".
    * ``fleet_p99_ms`` — closed-loop end-to-end p99 over the fleet
      window (banded like ``serve_p99_ms``).
    * ``router_spill_share`` — spilled / routed requests over the
      window (the shared lower-better ``_share`` marker).
    """
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability import MetricsRegistry
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.serving import ServingPlane, model_charge
    from keystone_tpu.serving.fleet import FleetController
    from keystone_tpu.serving.loadgen import LoadSpec, generate_trace
    from keystone_tpu.serving.loadgen import replay as replay_trace
    from keystone_tpu.serving.router import FleetRouter, LocalReplicaClient

    n_dev = len(jax.devices())
    n_replicas = 3
    d, k = (64, 10) if SMALL else (256, 10)
    n_fit = 512 if SMALL else _scaled(4_096, mult=512, floor=1_024)
    max_batch = 32 if SMALL else 64
    window_s = 2.0 if SMALL else float(_scaled(8, mult=1, floor=4))
    base_clients = 4
    fleet_clients = base_clients * n_replicas

    r = np.random.RandomState(7)
    X = r.rand(n_fit, d).astype(np.float32)
    Y = r.rand(n_fit, k).astype(np.float32)
    fitted = LinearMapEstimator(lam=1e-3).with_data(
        ArrayDataset.from_numpy(X),
        ArrayDataset.from_numpy(Y)).fit()
    sample = jax.ShapeDtypeStruct((d,), np.float32)
    charge = model_charge(fitted, sample, max_batch).total_nbytes()
    # six names over one fitted model: equal charges make the FFD
    # spread deterministic (two per replica) and keep the section's
    # fit cost at one solve
    names = tuple(f"m{i}" for i in range(2 * n_replicas))

    reg = MetricsRegistry.get_or_create()
    # one trace, two replays: the fleet at full speed, the single
    # plane time-stretched x n_replicas (per-replica rate, identical
    # request sequence)
    spec = LoadSpec(
        seed=9, duration_s=window_s, rate_rps=1_200.0,
        arrival="bursty", models=names, zipf_s=1.2,
        sizes=(4, 8, max_batch // 2),
        burst_mult=2.0, burst_on_s=0.5, burst_off_s=0.25)
    trace = generate_trace(spec)

    def input_for(model, n):
        return X[:n]

    def warm(plane, hosted):
        # pay the per-bucket serve compiles BEFORE the measured
        # window: the single window runs at light load and a cold
        # bucket compile would stretch its wall into the qps
        for name in hosted:
            for n in (1, max_batch):
                plane.predict(name, X[:n], timeout_s=60.0)

    def run_window(target, senders, time_scale):
        """One closed-loop load window; returns (rows/sec, sorted
        latencies ms, report)."""
        rows0 = reg.counter("serving.rows_total").value
        report = replay_trace(
            trace, target, input_for, senders=senders,
            time_scale=time_scale,
            submit_timeout_s=30.0, result_timeout_s=60.0)
        broken = (report.outcomes["error"]
                  + report.outcomes["unclassified"]
                  + report.outcomes["poisoned"])
        if broken:
            raise RuntimeError(
                f"{broken} request(s) FAILED in the fault-free fleet "
                f"window: {report.errors[:4]}")
        lat_ms = np.sort(np.asarray(report.latencies_ms, np.float64))
        if lat_ms.size == 0:
            raise RuntimeError("fleet window completed zero requests")
        qps = (reg.counter("serving.rows_total").value
               - rows0) / report.wall_s
        return qps, lat_ms, report

    def make_plane(budget):
        plane = ServingPlane(hbm_budget=budget, max_batch=max_batch,
                             queue_depth=1024)
        plane.start()
        return plane

    # -- single-replica baseline: one saturated plane, all six models
    base_plane = make_plane(len(names) * charge + (1 << 20))
    planes = []
    try:
        for name in names:
            base_plane.admit(name, fitted, sample, weight_dtype=None)
        warm(base_plane, names)
        u0 = base_plane.unexpected_recompiles()
        single_qps, _, _ = run_window(
            base_plane, base_clients, time_scale=float(n_replicas))
        if base_plane.unexpected_recompiles() - u0:
            raise RuntimeError(
                "steady-state recompile in the fleet baseline window")

        # -- the fleet: 3 planes, placement solved under budgets that
        # fit two homes plus ONE earned replica copy each
        planes = [make_plane(int(3.3 * charge) + (1 << 20))
                  for _ in range(n_replicas)]
        clients = [LocalReplicaClient(f"r{i}", plane)
                   for i, plane in enumerate(planes)]
        # closed-loop senders keep per-plane depth <= sender count, so
        # the proactive-spill threshold sits BELOW it: a primary with
        # a couple queued loses the request to an idler sibling
        router = FleetRouter(clients, spill_queue_depth=2)
        controller = FleetController(router, bucket_rows=max_batch)
        for i, name in enumerate(names):
            # the two Zipf-hottest names carry demand, so the solver
            # replicates exactly them into the leftover budget
            qps = 500.0 if i < 2 else 0.0
            controller.register(name, fitted, sample, qps=qps,
                                warmup_s=1.0 if qps else 0.0)
        for client in clients:
            controller.set_budget(client.replica_id, 3.3 * charge)
        controller.rebalance()
        placed = controller.placement
        copies = {name: len(placed.replicas_for(name))
                  for name in names}
        for client in clients:
            warm(client.plane, client.models())

        u1 = sum(p.unexpected_recompiles() for p in planes)
        routed0 = reg.counter("router.requests_total").value
        spill0 = reg.counter("router.spill_total").value
        fleet_qps, lat_ms, report = run_window(
            router, fleet_clients, time_scale=1.0)
        if sum(p.unexpected_recompiles() for p in planes) - u1:
            raise RuntimeError(
                "steady-state recompile in the fleet scale-out window")
        routed = reg.counter("router.requests_total").value - routed0
        spilled = reg.counter("router.spill_total").value - spill0
        spill_share = spilled / routed if routed else 0.0
        scaling = fleet_qps / single_qps if single_qps else 0.0

        common = dict(
            replicas=n_replicas, models=len(names),
            clients=fleet_clients, window_s=round(report.wall_s, 2),
            max_batch=max_batch,
            loadgen=dict(seed=spec.seed, arrival=spec.arrival,
                         rate_rps=spec.rate_rps, zipf_s=spec.zipf_s),
            single_qps=round(single_qps / n_dev, 1),
            scaling=round(scaling, 3),
            copies=copies,
            spilled=int(spilled), routed=int(routed),
            unexpected_recompiles=0,
        )
        _emit("fleet_qps", round(fleet_qps / n_dev, 1),
              "rows/sec/chip",
              round(fleet_qps / (2.4 * single_qps), 4)
              if single_qps else 0.0, **common)
        _emit("fleet_p99_ms",
              round(float(np.percentile(lat_ms, 99)), 3), "ms",
              round(float(np.percentile(lat_ms, 99)) / 10.0, 4),
              **common)
        _emit("router_spill_share", round(spill_share, 4), "share",
              round(spill_share / 0.5, 4), **common)
    finally:
        base_plane.close()
        for plane in planes:
            plane.close()


def elastic_coordination_bench():
    """Multi-host coordination cost on the CPU dryrun harness (PR 18):
    shells out to ``tools/elastic_bench.py`` — real ``jax.distributed``
    + gloo worlds at sizes 1 and 2, warm steady-state fits — and
    re-emits its banded lines:

    * ``elastic_scaling_efficiency`` — (2p img/s) / (2 x 1p img/s);
      vs_baseline against the 0.8 acceptance bar. On the CPU sim both
      "hosts" share this machine, so the number prices coordination
      rounds, not hardware scaling; warm per-chunk wall is dispatch-
      latency-bound under gloo, so values above 1.0 mean the hosts
      overlap that latency (coordination adds ~nothing).
    * ``coord_overhead_share`` — blocked-await wall / round wall on the
      2-process world (PERFORMANCE.md rule 17: measure the await, not
      the round). Banded absolutely via the shared "overhead_share"
      marker; the overlapped round loop's whole point is holding this
      near zero.
    * ``coord_overlap_occupancy`` — its complement (1.0 = coordination
      fully hidden behind accumulate compute).

    The subprocess pins ``JAX_PLATFORMS=cpu`` for the worlds, so this
    section is device-independent — it measures the coordinator, not
    the accelerator, and runs identically on the TPU bench host."""
    import subprocess
    import sys as _sys

    rows = 4_096 if SMALL else _scaled(16_384, mult=4_096)
    proc = subprocess.run(
        [_sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "elastic_bench.py"),
         "--rows", str(rows), "--chunk-size", "256"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if proc.returncode != 0:
        raise RuntimeError(
            f"elastic bench subprocess failed (rc {proc.returncode}): "
            f"{proc.stdout[-800:]}\n{proc.stderr[-800:]}")
    lines = {}
    for raw in proc.stdout.splitlines():
        if raw.startswith("{"):
            blob = json.loads(raw)
            lines[blob.get("metric")] = blob
    eff = lines.get("elastic_scaling_efficiency")
    if eff is None:
        raise RuntimeError("elastic bench emitted no "
                           f"efficiency line: {proc.stdout[-800:]}")
    _emit("elastic_scaling_efficiency", round(float(eff["value"]), 4),
          "fraction", round(float(eff["value"]) / 0.8, 3),
          processes=eff.get("processes"), rows=rows,
          note=eff.get("note"))
    share = lines.get("coord_overhead_share")
    if share is not None:
        _emit("coord_overhead_share", round(float(share["value"]), 6),
              "share", round(float(share["value"]) / 0.02, 3),
              processes=share.get("processes"))
    occ = lines.get("coord_overlap_occupancy")
    if occ is not None:
        _emit("coord_overlap_occupancy", round(float(occ["value"]), 6),
              "fraction", round(float(occ["value"]) / 0.98, 3),
              processes=occ.get("processes"))


def loader_bench():
    """VERDICT r2 weak#5: time the tar -> threaded decode -> device ->
    SIFT path END TO END on a generated JPEG tar, so the ImageNet-style
    ingest is measured with the loader in the loop rather than
    shapes-only. The pipeline is the production shape: tar streams
    sequentially, PIL decode runs on the loader thread pool
    (``iter_decoded_chunks``), each chunk is device_put as uint8 (4x
    smaller than f32 on the wire) and featurized under one async
    dispatch — JAX overlaps the next chunk's decode with the device
    work. No published baseline; vs_baseline against a 100 images/sec
    strawman (reference ImageNetLoader fed cluster executors from HDFS
    tars, ``ImageLoaderUtils.scala:23-94``).

    Note: on the axon bench chip the host->device link is a dev tunnel
    at single-digit MB/s, so the uint8 upload — not decode or SIFT — can
    dominate; the breakdown keys make that attribution visible.
    """
    import tarfile as tarmod
    import tempfile

    from keystone_tpu.loaders.image_loader_utils import iter_decoded_chunks
    from keystone_tpu.nodes.images.extractors import SIFTExtractor

    n_imgs = 64 if SMALL else _scaled(512, mult=64, floor=128)
    side = 128
    chunk = 16 if SMALL else 64
    tar_path = os.path.join(
        tempfile.gettempdir(),
        f"keystone_bench_{os.getuid()}_{n_imgs}_{side}.tar")

    def _tar_valid(path):
        try:
            with tarmod.open(path, "r") as tf:
                return sum(1 for e in tf if e.isfile()) == n_imgs
        except Exception:
            return False

    if not (os.path.exists(tar_path) and _tar_valid(tar_path)):
        from PIL import Image as PILImage
        import io

        rng = np.random.RandomState(0)
        base = (rng.rand(side, side, 3) * 255).astype(np.uint8)
        # atomic publish: a run killed mid-write must not leave a
        # truncated tar that poisons every later run on this host
        tmp_path = tar_path + f".tmp{os.getpid()}"
        with tarmod.open(tmp_path, "w") as tf:
            for i in range(n_imgs):
                arr = np.roll(base, 3 * i, axis=0)  # distinct per entry
                buf = io.BytesIO()
                PILImage.fromarray(arr).save(buf, format="JPEG", quality=90)
                data = buf.getvalue()
                info = tarmod.TarInfo(f"class{i % 10}/img{i:05d}.jpg")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        os.replace(tmp_path, tar_path)

    sift = SIFTExtractor(step=8, bin_size=4, num_scales=2, scale_step=1)

    @jax.jit
    def featurize_chunk(imgs_u8):
        # NTSC grayscale on device (u8 wire format, f32 compute)
        f = imgs_u8.astype(jnp.float32) / 255.0
        gray = (0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2])
        descs = jax.vmap(sift.apply)(gray)
        return jnp.sum(descs, axis=(1, 2))  # keep the d2h pull tiny

    def run_pipeline():
        outs = []
        for batch in iter_decoded_chunks([tar_path], chunk):
            arr = np.stack([img for _, img in batch]).astype(np.uint8)
            if arr.shape[0] != chunk:  # static jit shape: pad the tail
                pad = np.zeros((chunk - arr.shape[0],) + arr.shape[1:],
                               np.uint8)
                arr = np.concatenate([arr, pad])
            outs.append(featurize_chunk(jax.device_put(arr)))
        _fence(outs)
        return len(outs)

    run_pipeline()  # warm: XLA compile + page cache

    # decode-only pass: attribution for the breakdown keys
    t0 = time.perf_counter()
    n_decoded = sum(len(b) for b in iter_decoded_chunks([tar_path], chunk))
    decode_dt = time.perf_counter() - t0

    # this section's spread is dominated by tunnel-bandwidth swings (the
    # ~25 MB of uint8 uploads move at single-digit MB/s); the r3->r4
    # "regression" (155 -> 82 img/s) sits entirely inside that band —
    # the median + spread keys make it visible instead of alarming
    e2e_dt, ev = _timed_median(run_pipeline)

    per_sec = n_imgs / e2e_dt
    _emit("tar_loader_sift_images_per_sec", round(per_sec, 1), "images/sec",
          round(per_sec / 100.0, 4),
          decode_only_images_per_sec=round(n_decoded / decode_dt, 1),
          image_side=side, n_images=n_imgs,
          overlap_efficiency=round(decode_dt / e2e_dt, 3), **ev)

    # -- streamed path: decode AND device_put move to the prefetch
    # thread (StreamingDataset, depth 2), so ingest of chunk i+1
    # overlaps the device work on chunk i. The serial path above pays
    # the host->device upload inline per chunk — on the tunneled bench
    # chip that upload dominates, which is exactly the overlap a double
    # buffer recovers. Stall share comes from the process metrics.
    from keystone_tpu.loaders.image_loader_utils import stream_tar_images

    depth = 2

    def prepare(batch):
        # no tail padding here: _stage pads every chunk to chunk_size
        # and keeps the TRUE row count in chunk.n — pre-padding would
        # count zero images as real rows in any downstream carry
        return np.stack([img for _, img in batch]).astype(np.uint8)

    def run_streamed():
        stream = stream_tar_images([tar_path], chunk, prepare=prepare,
                                   n=n_imgs, prefetch_depth=depth)
        outs = [featurize_chunk(c.data) for c in stream.chunks()]
        _fence(outs)
        return len(outs)

    from keystone_tpu.observability import compile_observatory

    compile_wall0 = compile_observatory().wall_s_total()
    run_streamed()  # warm (compiles are shared with the serial path)
    share = _ingest_stall_probe(-(-n_imgs // chunk), n_imgs)
    s_dt, s_ev = _timed_median(run_streamed, warmup_fence=True,
                               compile_wall0=compile_wall0)
    s_per_sec = n_imgs / s_dt
    _emit("tar_loader_sift_streamed_images_per_sec", round(s_per_sec, 1),
          "images/sec", round(s_per_sec / 100.0, 4),
          prefetch_depth=depth,
          speedup_vs_serial=round(e2e_dt / s_dt, 3),
          ingest_stall_share=share(s_dt),
          h2d_bytes_per_image=share.h2d_bytes_per_image(),
          image_side=side, n_images=n_imgs, **s_ev)


# ----------------------------------------- streamed out-of-core e2e bench


def streamed_e2e_bench():
    """Streamed CIFAR end-to-end (the out-of-core path): host uint8
    chunks -> double-buffered device ingest (StreamingDataset, depth 2)
    -> per-chunk fused featurization -> BlockLS Gram/cross ACCUMULATE ->
    finalize -> streamed predict. The featurized training matrix never
    exists in HBM — device residency is the bounded prefetch buffer plus
    one chunk of features plus the (F, F) carry, and the ingest buffer
    is asserted against an explicit budget via
    ``parallel.dataset.device_nbytes``. vs_baseline shares the resident
    e2e's 10k img/s/chip strawman (expect a lower number: this path
    pays real host->device ingest, which the resident bench stages
    outside the timed region — the metric is the OVERLAPPED ingest
    cost, not a regression)."""
    from keystone_tpu.nodes.learning.linear import BlockLeastSquaresEstimator
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import (
        fused_cifar_featurize,
        use_pallas,
    )
    from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming

    n_dev = len(jax.devices())
    num_filters = 64 if SMALL else 256
    patch = 6
    chunk = 256 if SMALL else 1_024
    n_train = 1_024 if SMALL else _scaled(8_192, mult=1_024, floor=2_048)
    n_test = 256 if SMALL else _scaled(2_048, mult=1_024, floor=1_024)
    depth = 2
    F = num_filters * 2 * 2 * 2

    rng = np.random.RandomState(7)
    filters = rng.randn(num_filters, patch * patch * 3).astype(np.float32)

    from keystone_tpu.observability import observed_jit

    # observed sites: the utilization window totals flops x calls over
    # every program that ran, so the section's featurize must be a
    # watched jit, not an anonymous bench-local one
    if use_pallas():
        @functools.partial(observed_jit, name="e2e_featurize")
        def featurize(imgs_u8):
            return fused_cifar_featurize(
                imgs_u8.astype(jnp.float32), jnp.asarray(filters), 32,
                patch, 3, 13, 14, 10.0, 0.25)
    else:
        @functools.partial(observed_jit, name="e2e_featurize")
        def featurize(imgs_u8):
            def one(img):
                conv = filter_bank_convolve(
                    img, jnp.asarray(filters), patch, 3, True, None, 10.0)
                pos = jnp.maximum(0.0, conv - 0.25)
                neg = jnp.maximum(0.0, -conv - 0.25)
                return pool_image(
                    jnp.concatenate([pos, neg], -1), 13, 14, "identity",
                    "sum").reshape(-1)

            return jax.vmap(one)(imgs_u8.astype(jnp.float32))

    # uint8 on the wire (4x smaller than f32); chunk labels are sliced
    # from the resident (n, 10) matrix — tiny next to the images
    imgs_host = (rng.rand(n_train, 32, 32, 3) * 255).astype(np.uint8)
    y = rng.randint(0, 10, n_train)
    L = (-np.ones((n_train, 10)) + 2.0 * np.eye(10)[y]).astype(np.float32)
    imgs_test = (rng.rand(n_test, 32, 32, 3) * 255).astype(np.uint8)

    # the ingest buffer may hold depth staged chunks + one working
    # chunk; anything beyond that margin means the stream is NOT
    # bounded and the out-of-core claim is false — fail loudly
    chunk_raw = chunk * 32 * 32 * 3
    budget = (depth + 1) * chunk_raw + (1 << 20)

    est = BlockLeastSquaresEstimator(min(1024, F), 1, lam=0.1)

    def feat_chunks(u8_stream):
        return u8_stream.map_chunks(lambda ad: ad.map_batch(featurize))

    result = {}

    def fit_and_predict():
        train = StreamingDataset.from_numpy(
            imgs_host, chunk_size=chunk, prefetch_depth=depth,
            tag="cifar-stream-train")
        model = fit_streaming(est, feat_chunks(train), L,
                              hbm_budget=budget)
        result["peak_stream"] = train.peak_device_nbytes
        # device-free planner prediction for the SAME stream geometry:
        # plan >= measured always (the ledger can never exceed it), and
        # plan/measured near 1 means the buffer saturated as modeled
        result["static_plan"] = train.static_plan_nbytes()
        test = StreamingDataset.from_numpy(
            imgs_test, chunk_size=chunk, prefetch_depth=depth,
            tag="cifar-stream-test")
        preds = []
        for out in model.apply_dataset(feat_chunks(test)).chunks():
            preds.append(np.asarray(
                jnp.argmax(out.data, axis=-1))[: out.n])
        result["preds"] = np.concatenate(preds)

    from keystone_tpu.observability import compile_observatory

    compile_wall0 = compile_observatory().wall_s_total()
    fit_and_predict()  # warm: one compile per chunk shape, then zero

    from keystone_tpu.observability.utilization import UtilizationWindow

    share = _ingest_stall_probe(
        -(-n_train // chunk) + -(-n_test // chunk), n_train + n_test)
    with UtilizationWindow() as uw:
        dt, ev = _timed_median(fit_and_predict, warmup_fence=True,
                               compile_wall0=compile_wall0)

    # numerics-overhead A/B pairs (PERFORMANCE.md rule 12): the OFF leg
    # runs the SAME warm path under numerics_suppressed() — the runtime
    # gate, no recompile, identical programs — so the difference is
    # purely the plane's per-chunk health words + sketch updates.
    # INTERLEAVED single-run pairs, median of per-pair shares: the ON
    # and OFF halves of a pair are adjacent in time, so slow machine
    # drift (the dominant noise on shared CPU-sim boxes, spreads up to
    # ~0.3 between sequential medians) cancels within each pair.
    # Tracked as a banded lower-is-better metric; the bar is <2% on
    # hardware (negative = below machine noise).
    from keystone_tpu.observability.numerics import numerics_suppressed

    def _single(suppress):
        t0 = time.perf_counter()
        if suppress:
            with numerics_suppressed():
                fit_and_predict()
        else:
            fit_and_predict()
        return time.perf_counter() - t0

    pair_shares = []
    for _ in range(3 if SMALL else 2):
        t_on = _single(False)
        t_off = _single(True)
        if t_off > 0:
            pair_shares.append((t_on - t_off) / t_off)
    overhead_share = (sorted(pair_shares)[len(pair_shares) // 2]
                      if pair_shares else None)
    # hardware denominator (PERFORMANCE.md rule 11): achieved FLOP/s
    # over device peak and bytes/s over HBM bandwidth, from the compile
    # observatory's per-executable cost_analysis x observed call counts
    util = uw.report(n_devices=n_dev)

    per_chip = (n_train + n_test) / dt / n_dev
    plan = result.get("static_plan")
    peak = result["peak_stream"]
    _emit("cifar_streamed_e2e_images_per_sec_per_chip", round(per_chip, 1),
          "images/sec/chip", round(per_chip / 10000.0, 4),
          chunk_size=chunk, prefetch_depth=depth, n_train=n_train,
          num_filters=num_filters,
          hbm_budget_mib=round(budget / (1 << 20), 2),
          peak_stream_mib=round(peak / (1 << 20), 2),
          # planner validation (BENCH_r06+): static_plan_hbm_mib is the
          # device-free prediction, plan_vs_measured its ratio to the
          # ledger peak (>= 1.0 by construction; ~1.0 = saturated
          # double buffer, large = the producer never filled the slots)
          static_plan_hbm_mib=(None if plan is None
                               else round(plan / (1 << 20), 2)),
          plan_vs_measured=(None if plan is None or not peak
                            else round(plan / peak, 3)),
          gram_carry_mib=round((F * F + F * 10) * 4 / (1 << 20), 2),
          ingest_stall_share=share(dt),
          h2d_bytes_per_image=share.h2d_bytes_per_image(),
          numerics_overhead_share=(None if overhead_share is None
                                   else round(overhead_share, 4)),
          e2e_mfu=round(util["mfu"], 5),
          e2e_membw_util=round(util["membw_util"], 5),
          roofline_bound=util["bound"],
          utilization_covered_sites=len(util["covered_sites"]),
          utilization_uncovered_sites=len(util["uncovered_sites"]),
          **ev)


def _section_cleanup():
    """Drop cross-section state so one section's HBM residue (datasets,
    prefix-cached fitted results) can't starve the next."""
    import gc

    try:
        _clear_prefix_state()
    except Exception:
        pass
    gc.collect()


def _run_section(section, deadline=None):
    """Run one section with buffered emission and one retry (the dev
    tunnel's compile service throws transient errors — "response body
    closed before all bytes were read" — that succeed on a second
    attempt). Lines reach stdout only when the section completes, so a
    failed attempt can never leave stale duplicate metric lines. The
    retry is forgone when the budget deadline has passed: a slow
    failing section must not run twice and push the process into the
    driver's kill window. Returns the attempt count on success (1 =
    clean first try — the only wall time worth persisting as a duration
    estimate), 0 on failure."""
    global _section_buffer
    import sys
    import traceback

    for attempt in (0, 1):
        _section_buffer = []
        try:
            section()
            for line in _section_buffer:
                _flush_line(line)
            return attempt + 1
        except Exception:
            # stdout, not stderr: the driver captures stdout, so the
            # evidence of a failed section survives in BENCH_r*.json
            traceback.print_exc(file=sys.stdout)
            if attempt == 0:
                if deadline is not None and time.monotonic() > deadline:
                    print(f"not retrying {section.__name__}: budget "
                          "deadline passed", flush=True)
                    return False
                print(f"retrying section {section.__name__} after "
                      "failure", flush=True)
                _section_cleanup()
                time.sleep(5)
        finally:
            _section_buffer = None
    return 0


def main():
    """Emit every BASELINE metric, one JSON line each, highest-priority
    sections first (flagship throughput, solver TFLOPS, accuracy — the
    round-2 timeout lost everything ordered after the apps). After every
    section the flagship summary line is re-emitted, so the LAST stdout
    line — what the driver parses as the headline — is always
    ``cifar_randompatch_images_per_sec_per_chip`` carrying every value
    measured so far, no matter where the run is cut off.

    Budgeting (VERDICT r5 weak#1): estimates come from MEASURED
    per-section durations persisted in ``.bench_durations.json`` by
    previous runs on this host (hardcoded values are only the cold
    fallback — stale estimates are what skipped 4-5 sections in r4/r5).
    A section whose estimate exceeds the remaining budget is SHRUNK
    (``_SCALE`` scales its n/reps; its metric lines carry a ``scaled``
    key), never skipped: every metric that has ever appeared in a
    BENCH_r*.json appears in every run."""
    global _SCALE
    # (section, fallback cost estimate in seconds — used only until a
    # measured duration exists for this host)
    # Ordering (r4 weak#1): after the flagship trio, least-recently-
    # measured sections run before well-covered repeats, so a budget
    # shortfall shrinks repeat measurements, not first measurements.
    sections = (
        (featurize_bench, 15),
        (solver_bench, 90),
        (accuracy_bench, 90),
        (voc_bench, 90),
        (amazon_bench, 25),
        (stupid_backoff_bench, 15),
        (imagenet_rehearsal_bench, 130),
        (pallas_kernels_bench, 60),
        (serving_bench, 45),
        (serving_soak_bench, 40),
        (fleet_bench, 50),
        (e2e_bench, 60),
        (loader_bench, 60),
        (streamed_e2e_bench, 60),
        (elastic_coordination_bench, 75),
        (newsgroups_bench, 30),
        (timit_bench, 120),
        (mnist_bench, 75),
    )
    # SMALL smoke runs neither consult nor record durations: their
    # seconds-long sections would poison the full-run budget estimates
    measured = {} if SMALL else _load_durations()
    deadline = _START + BUDGET_S
    _emit_meta()  # host identity up front: survives a cut-short run
    for section, fallback in sections:
        est = 1.15 * measured.get(section.__name__, fallback)
        remaining = deadline - time.monotonic()
        if remaining >= est:
            _SCALE = 1.0
        else:
            # over budget: shrink, don't skip — a scaled number beats a
            # missing one (flagged via the "scaled" metric key). With
            # the deadline already passed (remaining <= 0) the section
            # still runs at the floor scale: BUDGET_S keeps >2 min of
            # margin under the driver's kill window precisely so a few
            # floor-scaled trailing sections fit inside it.
            _SCALE = max(_MIN_SCALE,
                         min(1.0, 0.8 * max(remaining, 0.0) / est))
            _scaled_sections.add(section.__name__)
            print(f"# shrinking {section.__name__} to scale "
                  f"{_SCALE:.2f}: {remaining:.0f}s of budget left < "
                  f"{est:.0f}s estimate", flush=True)
        t_sec = time.monotonic()
        attempts = _run_section(section, deadline)
        took = time.monotonic() - t_sec
        if attempts == 1 and not SMALL:
            if _SCALE == 1.0:
                _record_duration(section.__name__, took)
            elif section.__name__ in measured:
                # scaled runs never extrapolate (the ratchet-UP trap),
                # but an inflated estimate must also not stick forever
                # — a one-off cold-compile wall would otherwise shrink
                # this section on every future run. Decay it toward the
                # observed scaled wall (never below it), so the
                # estimate heals and the section re-earns full size.
                _record_duration(section.__name__,
                                 max(took, 0.85 * measured[section.__name__]))
        _SCALE = 1.0
        _section_cleanup()
        print(f"# {section.__name__} took {took:.0f}s", flush=True)
        _emit_summary()
    if _emitted == 0:
        # every section failed: fail loudly instead of exiting 0 with an
        # empty metrics stream
        raise SystemExit(1)
    _emit_meta()  # refresh: now carries the complete scaled list
    # The LAST stdout JSON line must be a metric line: the flagship
    # summary when available, else the flagship alone, else the best
    # (first-emitted) surviving metric.
    flag = _metrics.get(FLAGSHIP)
    if flag is not None and len(_metrics) >= 2:
        _emit_summary()
    elif flag is not None:
        print(json.dumps(flag), flush=True)
    else:
        print(json.dumps(next(iter(_metrics.values()))), flush=True)


def _pop_trace_out(argv):
    """Extract ``--trace-out PATH`` from argv (None when absent)."""
    if "--trace-out" not in argv:
        return None
    i = argv.index("--trace-out")
    if i + 1 >= len(argv):
        raise SystemExit("--trace-out requires a path")
    path = argv[i + 1]
    del argv[i:i + 2]
    return path


if __name__ == "__main__":
    import sys

    _enable_compilation_cache()
    sections = {
        "--solver": solver_bench,
        "--accuracy": accuracy_bench,
        "--imagenet": imagenet_rehearsal_bench,
        "--e2e": e2e_bench,
        "--featurize": featurize_bench,
        "--mnist": mnist_bench,
        "--timit": timit_bench,
        "--newsgroups": newsgroups_bench,
        "--loader": loader_bench,
        "--amazon": amazon_bench,
        "--stupid-backoff": stupid_backoff_bench,
        "--voc": voc_bench,
        "--streamed-e2e": streamed_e2e_bench,
        "--serving": serving_bench,
        "--serving-soak": serving_soak_bench,
        "--fleet": fleet_bench,
    }
    argv = list(sys.argv[1:])
    trace_out = _pop_trace_out(argv)
    picked = [f for f in argv if f in sections]
    unknown = [f for f in argv if f.startswith("--")
               and f not in sections]
    if unknown:
        raise SystemExit(f"unknown bench flags {unknown}; "
                         f"known: {sorted(sections)} plus --trace-out PATH")

    def _run_all():
        if picked:
            _emit_meta()  # single-section runs carry host identity too
            for f in picked:
                sections[f]()
        else:
            main()

    if trace_out is None:
        _run_all()
    else:
        # bench numbers should travel with their execution evidence
        # (PERFORMANCE.md): the trace JSON records per-node wall times,
        # optimizer rule log, auto-cache report, and solver decisions;
        # a *.perfetto.json path writes the flight recorder's Chrome
        # trace instead (ingest/H2D/compute lanes — the overlap
        # evidence, viewable at https://ui.perfetto.dev)
        from keystone_tpu.observability import (
            PipelineTrace,
            write_trace_artifact,
        )

        with PipelineTrace("bench") as _tr:
            _run_all()
        _kind = write_trace_artifact(trace_out, _tr)
        print(_tr.summary(top=30), file=sys.stderr)
        print(f"# {_kind} written to {trace_out}", file=sys.stderr)
